"""Mixed-precision compute mode: bf16 matmuls/convs with fp32 master weights.

TPU-native equivalent of the reference's float16 transpiler
(ref: paddle/contrib/float16/float16_transpiler.py, which rewrites a program
so inference runs in fp16).  The reference rewrites the *program* because its
kernels are dtype-monomorphic; here the op library itself is polymorphic, so
mixed precision is an execution mode: when enabled, the matmul-class ops
(mul/matmul/fc, conv2d/3d and friends) cast fp32 operands to the compute
dtype and accumulate in fp32 via ``preferred_element_type``.

This is exactly the TPU-idiomatic recipe: parameters, optimizer state,
normalizations and reductions stay fp32 (master weights), while the
MXU-bound contractions run in the low dtype.  The contraction itself
executes entirely in that dtype (the MXU accumulates bf16 products in fp32
*in hardware*; there is no explicit preferred_element_type — its vjp rules
reject mixed cotangent/operand dtypes for convs).  Consequences:

 - "bfloat16" (recommended, the default): same exponent range as fp32, no
   loss scaling needed; hardware fp32 accumulation makes operand rounding
   the only precision loss.
 - "float16": the contraction accumulates in fp16 with fp16's narrow
   exponent range — usable for TRAINING because enabling it arms a
   **dynamic loss scaler** by default: the backward seed is multiplied by
   a persistable scale (so fp16 intermediate grads sit in representable
   range), the raw grads are divided back by the scale before clip and
   update (``clip.append_unscale_ops``), and the guarded executor step
   (``fluid.guardian``) grows the scale x2 every ``growth_interval``
   overflow-free steps, shrinks it /2 and SKIPS the update (device-side,
   bit-exact revert) on overflow.  The reference's fp16 transpiler
   targets *inference* (float16_benchmark.md); this is the training
   story it lacked.

Enable programmatically::

    import paddle_tpu.fluid as fluid
    fluid.amp.enable("bfloat16")          # or fluid.amp.amp_guard(...)

or via the environment: ``PADDLE_TPU_AMP=bfloat16``.
"""

from __future__ import annotations

import contextlib
import os

_SUPPORTED = ("bfloat16", "float16")

#: persistable scope vars carrying the dynamic loss-scale state; created by
#: Optimizer.minimize (via create_loss_scaling_vars) when scaling is active
#: at build time, updated device-side by guardian.fold_health every step
LOSS_SCALE_VAR = "@LOSS_SCALE@"
LOSS_SCALE_GOOD_VAR = "@LOSS_SCALE_GOOD@"

_state = {"dtype": None, "keep": False, "dynamic_scaling": None,
          "init_loss_scale": 2.0 ** 15, "scale_growth_interval": 1000}


def enable(dtype: str = "bfloat16", keep_activations=None,
           dynamic_loss_scaling=None, init_loss_scale=None,
           growth_interval=None) -> None:
    """Enable mixed precision.

    ``keep_activations=True`` selects the pure-low-precision activation
    regime: contraction outputs STAY in the compute dtype instead of being
    cast back to fp32, so inter-layer activations (the dominant HBM
    traffic of conv nets at scale) move at half the bytes.  Numerics keep
    the master-fp32 discipline everywhere it matters: parameters,
    optimizer state and gradients stay fp32 (the cast's transpose upcasts
    cotangents), batch_norm/layer_norm compute statistics in fp32, and
    softmax/cross-entropy upcast at the loss boundary.  This is the
    standard production-TPU training recipe (measured on the round-5
    tunnel: ~2x ResNet-50 step throughput — docs/PERF.md).
    Default: the PADDLE_TPU_AMP_KEEP env var, else False.
    """
    if dtype not in _SUPPORTED:
        raise ValueError(f"amp dtype must be one of {_SUPPORTED}, got {dtype!r}")
    _state["dtype"] = dtype
    if keep_activations is None:
        from . import envcontract

        keep_activations = bool(envcontract.get("PADDLE_TPU_AMP_KEEP"))
    _state["keep"] = bool(keep_activations)
    # dynamic loss scaling: None = auto (on for float16, pointless for
    # bfloat16 whose exponent range matches fp32); True/False force it.
    # Scaling is a BUILD-time decision — it threads scale vars and
    # seed/unscale ops through Optimizer.minimize — so set it before
    # building the train program.
    _state["dynamic_scaling"] = dynamic_loss_scaling
    if init_loss_scale is not None:
        _state["init_loss_scale"] = float(init_loss_scale)
    if growth_interval is not None:
        _state["scale_growth_interval"] = max(1, int(growth_interval))


def disable() -> None:
    _state["dtype"] = None
    _state["keep"] = False
    _state["dynamic_scaling"] = None


def dynamic_scaling_active() -> bool:
    """True when programs built NOW should carry dynamic loss scaling."""
    ds = _state["dynamic_scaling"]
    if ds is not None:
        return bool(ds) and _state["dtype"] is not None
    return _state["dtype"] == "float16"


def scaling_config():
    """(init_loss_scale, growth_interval) for the scaler being built."""
    return _state["init_loss_scale"], _state["scale_growth_interval"]


def create_loss_scaling_vars(program, startup_program):
    """Create (or reuse) the persistable loss-scale state vars in
    ``program`` and record them on it for the guarded executor step.
    Returns the scale Variable (read by the seed/unscale ops)."""
    from .framework import program_guard
    from .layers import tensor as _tensor

    block = program.global_block()
    with program_guard(program, startup_program):
        if block.has_var(LOSS_SCALE_VAR):
            scale = block.var(LOSS_SCALE_VAR)
        else:
            scale = _tensor.create_global_var(
                shape=[1], value=_state["init_loss_scale"], dtype="float32",
                persistable=True, name=LOSS_SCALE_VAR)
            _tensor.create_global_var(
                shape=[1], value=0, dtype="int32",
                persistable=True, name=LOSS_SCALE_GOOD_VAR)
    program._loss_scale_vars = (LOSS_SCALE_VAR, LOSS_SCALE_GOOD_VAR)
    program._loss_scale_growth = _state["scale_growth_interval"]
    return scale


def is_enabled() -> bool:
    return _state["dtype"] is not None


def compute_dtype():
    """The active low-precision compute dtype name, or None."""
    return _state["dtype"]


def keep_low_activations() -> bool:
    """True when AMP is on in the pure-low-activation regime."""
    return _state["dtype"] is not None and _state["keep"]


def is_low_float(dtype) -> bool:
    """True for sub-32-bit float dtypes (bf16/fp16) — THE predicate ops use
    to decide 'compute this norm/loss internally in fp32'.  Centralized so
    the regime's dtype policy has one definition."""
    import jax.numpy as jnp

    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16", keep_activations=None):
    prev = dict(_state)
    enable(dtype, keep_activations=keep_activations)
    try:
        yield
    finally:
        _state.update(prev)


def matmul(a, b):
    """``a @ b`` in the AMP compute dtype; identity when AMP is off.  The
    result is restored to fp32 in the default regime, or LEFT in the
    compute dtype under keep_activations.  The shared helper for code that
    contracts OUTSIDE the op library (stacked transformer, ring
    attention) — one policy, every path."""
    a2, b2, back = cast_operands(a, b)
    return restore_astype(a2 @ b2, back)


def einsum(spec, a, b):
    """Two-operand einsum under the same AMP recipe (and keep_activations
    behavior) as :func:`matmul`."""
    import jax.numpy as jnp

    a2, b2, back = cast_operands(a, b)
    return restore_astype(jnp.einsum(spec, a2, b2), back)


def cast_operands(*arrays):
    """Cast fp32 contraction operands to the AMP dtype.

    Returns ``(arrays..., restore_dtype)``.  Default regime: when AMP is
    off (or any operand is not fp32) the operands pass through unchanged
    and restore_dtype is None; otherwise the caller computes the
    contraction in the low dtype and casts its result back with
    ``restore_astype`` — NOT via ``preferred_element_type``, whose vjp
    rules reject mixed cotangent/operand dtypes for convs.  On the MXU
    this costs nothing: bf16 matmuls accumulate in fp32 internally.

    keep_activations regime: operands may arrive fp32 (params/feeds) or
    already in the compute dtype (upstream activations); fp32 ones are
    cast down, restore_dtype is None, and the result STAYS low — the
    whole point of the regime (half the inter-layer HBM bytes).
    """
    import jax.numpy as jnp

    d = _state["dtype"]
    if d is None:
        return (*arrays, None)
    cd = jnp.bfloat16 if d == "bfloat16" else jnp.float16
    if _state["keep"]:
        # pure-low-activation regime: operands may arrive fp32 (params,
        # feeds) or already in the compute dtype (upstream activations);
        # cast the fp32 ones down and DON'T restore — the contraction
        # result stays low so downstream layers read half the bytes.
        if any(a is None or a.dtype not in (jnp.float32, cd)
               for a in arrays):
            return (*arrays, None)
        return (*(a.astype(cd) if a.dtype == jnp.float32 else a
                  for a in arrays), None)
    if any(a is None or a.dtype != jnp.float32 for a in arrays):
        return (*arrays, None)
    return (*(a.astype(cd) for a in arrays), jnp.float32)


def restore_astype(out, restore_dtype):
    """Cast a contraction result back to the pre-AMP dtype (no-op when
    cast_operands passed through)."""
    return out if restore_dtype is None else out.astype(restore_dtype)


# environment bridge (ref: python/paddle/fluid/__init__.py:121-140 reads
# FLAGS from env at import time)
_env = os.environ.get("PADDLE_TPU_AMP", "").strip().lower()
if _env in ("bf16", "bfloat16", "1", "true"):
    enable("bfloat16")
elif _env in ("fp16", "float16"):
    enable("float16")
_env_scale = os.environ.get("PADDLE_TPU_AMP_INIT_SCALE", "").strip()
if _env_scale:
    _state["init_loss_scale"] = float(_env_scale)
_env_interval = os.environ.get("PADDLE_TPU_AMP_SCALE_INTERVAL", "").strip()
if _env_interval:
    _state["scale_growth_interval"] = max(1, int(_env_interval))
