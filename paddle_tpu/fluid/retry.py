"""Bounded retry for transient I/O on durable-state paths (ISSUE 18).

Every durable-state write the recovery machinery depends on — checkpoint
var files, ``_SUCCESS`` commits, census heartbeats and host-loss markers,
serving warmup manifests, compile-cache commits — used to treat the first
transient ``OSError`` as fatal (or, worse, as serial-condemning
corruption).  :func:`retry_io` is the one wrapper those call sites share:
``OSError`` means *transient* and earns bounded retry with exponential
backoff (``master.Backoff``, the reference Go master's reconnect pacing);
anything else — ``ValueError`` from a torn npy header, ``EOFError``,
``ReshardError`` — means *content*, is never retried, and keeps flowing
to the caller's existing condemnation/fallback path untouched.  That
split is the hardening contract the chaos drills verify: with
``PADDLE_FAULT_IO_ERROR_RATE`` armed, saves/loads succeed through
retries, while a genuinely corrupt serial still falls back.

Each retry is observable: one ``io.retry`` run event plus an
``io.retries{what=...}`` counter bump in the process registry — the
acceptance oracle ("retry counters nonzero in the observe stream") and
the postmortem's evidence that storage, not code, was flaky.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, TypeVar

from . import envcontract as _ec

__all__ = ["retry_io"]

T = TypeVar("T")

#: backoff ceiling between attempts — transients are sub-second events;
#: anything needing longer belongs to the supervisor's restart budget
_MAX_DELAY_S = 2.0


def retry_io(fn: Callable[[], T], *, what: str,
             attempts: Optional[int] = None,
             base_s: Optional[float] = None,
             sleep: Callable[[float], None] = time.sleep) -> T:
    """Run ``fn`` (a zero-arg I/O closure), retrying ``OSError`` up to
    ``attempts`` total tries with exponential backoff.

    ``what`` labels the call site (``ckpt.var_write``, ``census.
    heartbeat``, ...) in the retry counter and event stream.  Defaults
    come live from the env contract (``PADDLE_IO_RETRIES`` /
    ``PADDLE_IO_RETRY_BASE_S``), so a subprocess worker's env is honored
    without plumbing.  The final failure re-raises the last ``OSError``
    — callers keep exactly the error contract they had before the
    wrapper, just with transients absorbed."""
    if attempts is None:
        attempts = int(_ec.get("PADDLE_IO_RETRIES"))
    if base_s is None:
        base_s = float(_ec.get("PADDLE_IO_RETRY_BASE_S"))
    attempts = max(1, int(attempts))
    from ..parallel.master import Backoff

    backoff = Backoff(base=float(base_s), factor=2.0,
                      max_delay=_MAX_DELAY_S)
    last: Optional[OSError] = None
    for attempt in range(attempts):
        try:
            return fn()
        except OSError as exc:
            last = exc
            if attempt + 1 >= attempts:
                break
            try:
                from .. import observe as _observe

                _observe.registry().inc("io.retries",
                                        labels={"what": what})
                _observe.emit("io.retry", what=what, attempt=attempt + 1,
                              error=f"{type(exc).__name__}: {exc}")
            except Exception:
                pass  # telemetry must never fail the I/O it describes
            sleep(backoff.delay(attempt))
    assert last is not None
    raise last
