"""LayerHelper: shared param-creation/op-append plumbing for layers
(ref: python/paddle/fluid/layer_helper.py)."""

from __future__ import annotations

from . import core, unique_name
from .framework import Parameter, Variable, default_main_program, default_startup_program
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def get_parameter(self, name):
        """Look up an existing parameter by name (ref: layer_helper.py)."""
        v = self.main_program.global_block()._var_recursive(name)
        if not isinstance(v, Parameter):
            raise ValueError(f"var {name} is not a Parameter")
        return v

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} expects one input")
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        if len(attr) == 1 and length != 1:
            attr = [attr[0]] + [ParamAttr(**attr[0].__dict__.copy())
                                for _ in range(length - 1)]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        for i, a in zip(inputs, attrs):
            yield i, a

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for i in inputs:
            if dtype is None:
                dtype = i.dtype
            elif dtype != i.dtype:
                raise ValueError("all inputs must have the same dtype")
        return dtype

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = attr if isinstance(attr, ParamAttr) else ParamAttr._to_attr(attr)
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w"]))
        gb = self.main_program.global_block()
        if gb.has_var(attr.name):
            # Named reuse = weight tying (the scope is name-keyed, so same
            # name is same storage).  Return the existing Parameter instead
            # of re-creating it — and refuse a shape/dtype mismatch here,
            # where the offending layer is on the stack, rather than letting
            # a later op fail with an unrelated broadcast error.
            existing = gb.var(attr.name)
            if tuple(existing.shape) != tuple(shape) \
                    or core.convert_dtype(existing.dtype) \
                    != core.convert_dtype(dtype):
                raise ValueError(
                    f"parameter {attr.name!r} reused with shape {shape} "
                    f"dtype {dtype}, but it already exists with shape "
                    f"{existing.shape} dtype {existing.dtype}")
            return existing
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs(with_initializer=True))
        attr.initializer(sp, startup_block)
        # mirror in the main program
        return gb.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, persistable=False, stop_gradient=stop_gradient)

    # reference-era alias
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, stop_gradient=True, **kwargs)

    def set_variable_initializer(self, var, initializer):
        self.startup_program.global_block().create_var(
            name=var.name, shape=var.shape, dtype=var.dtype,
            persistable=True)
        initializer(var, self.startup_program.global_block())
        return var

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(
            type="elementwise_add", inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]}, attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name)
        if not isinstance(param, cls):
            raise TypeError(f"{param_name} must be {cls}")
