"""ParallelExecutor: data-parallel execution over a device mesh.

The reference's ParallelExecutor (ref: parallel_executor.cc:119, SSA-graph
engine in framework/details/) replicates the program per GPU and inserts NCCL
all-reduce op-handles per gradient.  The TPU-native equivalent needs none of
that machinery: the same traced block function is jitted under a 1-D
``jax.sharding.Mesh`` with the batch dimension of every fed tensor sharded
across devices and all state replicated.  XLA's SPMD partitioner then derives
the per-device program and inserts the gradient all-reduce collectives over
ICI automatically — the multi_devices_graph_pass, AllReduceOpHandle and
ThreadedSSAGraphExecutor collapse into GSPMD.

Loss scaling: the reference writes a 1/N constant per device
(ScaleLossGradOpHandle).  Here the loss `mean` already averages over the
*global* batch, so gradients match the single-device program exactly — the
"same loss single vs parallel" oracle (SURVEY.md §4.4) holds by construction.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from .executor import _MISSING, global_scope
from .framework import Variable, default_main_program
from ..parallel.mesh import env_mesh_spec, mesh_from_spec, mesh_label
from ..parallel.spmd import ShardedTrainStep, ShardedWindowRunner


class ExecutionStrategy:
    """ref: pybind.cc:605-620.  Most knobs are XLA's business now; kept for
    API parity and honored where meaningful."""

    class ExecutorType:
        Default = 0
        Experimental = 1

    def __init__(self):
        self.num_threads = 0
        self.use_cuda = False
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.type = ExecutionStrategy.ExecutorType.Default


class BuildStrategy:
    """ref: pybind.cc:621-643."""

    class ReduceStrategy:
        AllReduce = 0   # replicated params (psum grads) — GSPMD default
        Reduce = 1      # sharded optimizer states (ZeRO-1 style)

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor:
    """ref: python/paddle/fluid/parallel_executor.py:32.

    Single-process: a "dp" mesh over the local devices.  Multi-process: if
    the program carries DistributeTranspiler dist info (or num_trainers>1),
    the coordination service is joined (parallel.multihost) and the mesh
    spans ALL processes' devices — each process feeds its local batch shard
    and GSPMD runs one global program, which is the redesigned pserver path.

    BuildStrategy.ReduceStrategy.Reduce enables ZeRO-1 optimizer-state
    sharding (see parallel.spmd.infer_param_specs)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, use_tpu=None,
                 devices=None, mesh=None, **kwargs):
        from ..parallel import multihost as _mh

        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._scope = scope or global_scope()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()

        dist_info = getattr(self._program, "_dist_info", None) or {}
        if num_trainers > 1 and not dist_info:
            dist_info = {"trainers": num_trainers, "trainer_id": trainer_id}
        _mh.ensure_init(dist_info)
        self._multihost = _mh.process_count() > 1

        # mesh selection: explicit Mesh > explicit devices (1-D dp) >
        # spec string from _dist_info / PADDLE_TPU_MESH ("dp4,tp2") >
        # the degenerate all-devices dp mesh.  The spec path is how
        # DistributeTranspiler-annotated programs pick their topology.
        mesh_spec = mesh if isinstance(mesh, str) else None
        if mesh_spec is None and not isinstance(mesh, Mesh):
            mesh_spec = dist_info.get("mesh") or env_mesh_spec()
        if isinstance(mesh, Mesh):
            self._mesh = mesh
        elif devices is not None:
            self._devices = list(devices)
            self._mesh = (mesh_from_spec(mesh_spec, devices=self._devices)
                          if mesh_spec
                          else Mesh(np.array(self._devices), ("dp",)))
        elif mesh_spec:
            self._mesh = mesh_from_spec(mesh_spec)  # global device order
        else:
            self._mesh = _mh.global_mesh(("dp",))  # global when multihost
        self._devices = list(self._mesh.devices.reshape(-1))
        self._cache = {}
        self._window_cache = {}

    @property
    def device_count(self):
        return len(self._devices)

    @property
    def mesh(self):
        return self._mesh

    @property
    def mesh_label(self):
        return mesh_label(self._mesh)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, list):
            # per-device feed dicts: concatenate along batch
            merged: Dict[str, np.ndarray] = {}
            for d in feed:
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(v, 0) for k, v in merged.items()}
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        # normalize dtypes BEFORE the cache key so float64-from-list feeds
        # don't compile a duplicate executable
        gb_ = self._program.global_block()
        feed_arrays = {}
        for k, v in feed.items():
            arr = np.asarray(v)
            if gb_._has_var_recursive(k):
                want = core.np_dtype(gb_._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            feed_arrays[k] = arr

        from . import amp as _amp

        key = (id(self._program), self._program._version, tuple(fetch_names),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())),
               # execution-mode toggles invalidate compiled steps (same
               # contract as Executor.run's cache key)
               _amp.compute_dtype(),
               os.environ.get("PADDLE_TPU_FLASH", ""),
               os.environ.get("PADDLE_TPU_FUSED", ""))
        step = self._cache.get(key)
        if step is None:
            from .. import analysis as _analysis

            # pre-compile verifier: turns the runtime rejects below (and
            # the opaque GSPMD sharding errors) into named diagnostics
            _analysis.check_before_compile(
                self._program, feed=feed_arrays, fetch_list=fetch_names,
                mesh=self._mesh, kind="pe_run")
            if getattr(self._program, "_loss_scale_vars", None) is not None:
                # the per-step sharded path has no guarded wrapper: the
                # backward seed would go unscaled while append_unscale_ops
                # still divides grads by the scale — silently wrong math
                raise RuntimeError(
                    "dynamic fp16 loss scaling requires the windowed "
                    "sharded path: use ParallelExecutor.run_steps")
            zero1 = (self._build_strategy.reduce_strategy ==
                     BuildStrategy.ReduceStrategy.Reduce)
            step = ShardedTrainStep(
                self._program, list(feed_arrays), fetch_names, self._mesh,
                zero1=zero1, multihost=self._multihost)
            self._cache[key] = step

        self._check_initialized(step.plan)
        feed_dev = step.place_feed(feed_arrays)
        state_vals = step.place_state(self._scope)

        fetches, new_state = step(feed_dev, state_vals)
        for name, val in new_state.items():
            self._scope.set(name, val)
        if self._program._params_grads is not None:
            from ..observe import memory as _obsmem

            # ledger gauges only — per-step events would flood the stream
            _obsmem.note_scope_live(self._scope, scope_label="train",
                                    mesh=self.mesh_label, emit_event=False)
        if return_numpy:
            return [step.fetch_to_host(v) for v in fetches]
        return list(fetches)

    def run_steps(self, fetch_list, feed=None, n_steps=1,
                  feed_per_step=False, return_numpy=True):
        """N training steps in ONE dispatch over the mesh — the sharded
        twin of ``Executor.run_steps`` (same scan body via
        ``executor.build_window_fn``, guardian sentinel + dynamic fp16
        loss scale riding the carry), with the spec-table shardings pinned
        on the carried state and the mutable state donated.

        ``feed_per_step=True``: each feed array carries a leading
        ``n_steps`` dim and scanned step i consumes slice i; the batch
        (dim 1) shards over the mesh's dp axes and must divide them —
        indivisible batches raise a clear ValueError rather than an
        opaque XLA sharding error."""
        from . import amp as _amp
        from . import guardian as _guardian

        n_steps = int(n_steps)
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list or []]
        gb = self._program.global_block()
        feed_arrays = {}
        for k, v in dict(feed or {}).items():
            if isinstance(v, jax.Array):
                feed_arrays[k] = v
                continue
            arr = np.asarray(v)
            if gb._has_var_recursive(k):
                want = core.np_dtype(gb._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            feed_arrays[k] = arr

        guard = _guardian.for_program(self._program)
        key = (id(self._program), self._program._version,
               tuple(fetch_names), n_steps, bool(feed_per_step),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())),
               _amp.compute_dtype(),
               guard.cache_token() if guard is not None else None,
               os.environ.get("PADDLE_TPU_FLASH", ""),
               os.environ.get("PADDLE_TPU_FUSED", ""),
               self.mesh_label)
        runner = self._window_cache.get(key)
        if runner is None:
            from .. import analysis as _analysis
            from ..observe import trace as _trace

            with _trace.span("executor.trace", n_steps=n_steps,
                             mesh=self.mesh_label):
                # stacked (n_steps, batch, ...) windows verify as one step
                _analysis.check_before_compile(
                    self._program,
                    feed=({k: v[0] if getattr(v, "ndim", 0) > 0 else v
                           for k, v in feed_arrays.items()}
                          if feed_per_step else feed_arrays),
                    fetch_list=fetch_names, mesh=self._mesh,
                    kind="pe_run_steps")
                zero1 = (self._build_strategy.reduce_strategy ==
                         BuildStrategy.ReduceStrategy.Reduce)
                runner = ShardedWindowRunner(
                    self._program, list(feed_arrays), fetch_names,
                    self._mesh, n_steps=n_steps,
                    feed_per_step=feed_per_step, zero1=zero1,
                    multihost=self._multihost)
                self._window_cache[key] = runner
        self._check_initialized(runner.plan)
        return runner.run(feed_arrays, scope=self._scope,
                          return_numpy=return_numpy)

    def stage_window(self, window):
        """Place one stacked ``(n_steps, batch, ...)`` feed window with the
        mesh's window sharding (batch dim 1 over the dp axes) — the
        ``DevicePrefetcher`` ``stage_fn`` for sharded training, so window
        k+1 lands shard-placed while the device runs window k."""
        from ..parallel.spmd import batch_spec

        arrays = {k: np.asarray(v) for k, v in window.items()}
        bspec = batch_spec(self._mesh)
        axes = [ax for ax in bspec if ax is not None]
        div = 1
        for ax in axes:
            div *= self._mesh.shape[ax]
        out = {}
        for k, arr in arrays.items():
            divisible = arr.ndim > 1 and arr.shape[1] % div == 0
            spec = P(*([None] + list(bspec))) if divisible else P()
            out[k] = jax.device_put(arr, NamedSharding(self._mesh, spec))
        return out

    def _check_initialized(self, plan):
        gb = self._program.global_block()
        for name in plan.state_in:
            if self._scope.get(name, _MISSING) is _MISSING:
                if gb._has_var_recursive(name) and \
                        gb._var_recursive(name).is_data:
                    raise RuntimeError(f"Data variable '{name}' was not fed")
                raise RuntimeError(f"Variable '{name}' is not initialized; "
                                   f"run the startup program first")

    def bcast_params(self):
        """ref: parallel_executor.cc:234 BCastParamsToDevices — replication is
        expressed via sharding; nothing to broadcast eagerly."""
        return None
