"""ParallelExecutor: data-parallel execution over a device mesh.

The reference's ParallelExecutor (ref: parallel_executor.cc:119, SSA-graph
engine in framework/details/) replicates the program per GPU and inserts NCCL
all-reduce op-handles per gradient.  The TPU-native equivalent needs none of
that machinery: the same traced block function is jitted under a 1-D
``jax.sharding.Mesh`` with the batch dimension of every fed tensor sharded
across devices and all state replicated.  XLA's SPMD partitioner then derives
the per-device program and inserts the gradient all-reduce collectives over
ICI automatically — the multi_devices_graph_pass, AllReduceOpHandle and
ThreadedSSAGraphExecutor collapse into GSPMD.

Loss scaling: the reference writes a 1/N constant per device
(ScaleLossGradOpHandle).  Here the loss `mean` already averages over the
*global* batch, so gradients match the single-device program exactly — the
"same loss single vs parallel" oracle (SURVEY.md §4.4) holds by construction.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from .executor import BlockPlan, _MISSING, global_scope, trace_block
from .framework import RNG_STATE_VAR, Variable, default_main_program


class ExecutionStrategy:
    """ref: pybind.cc:605-620.  Most knobs are XLA's business now; kept for
    API parity and honored where meaningful."""

    class ExecutorType:
        Default = 0
        Experimental = 1

    def __init__(self):
        self.num_threads = 0
        self.use_cuda = False
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.type = ExecutionStrategy.ExecutorType.Default


class BuildStrategy:
    """ref: pybind.cc:621-643."""

    class ReduceStrategy:
        AllReduce = 0   # replicated params (psum grads) — GSPMD default
        Reduce = 1      # sharded optimizer states (ZeRO-1 style)

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor:
    """ref: python/paddle/fluid/parallel_executor.py:32."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, use_tpu=None,
                 devices=None, **kwargs):
        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._scope = scope or global_scope()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()
        if devices is not None:
            self._devices = list(devices)
        else:
            self._devices = list(jax.devices())
        self._mesh = Mesh(np.array(self._devices), ("dp",))
        self._cache = {}

    @property
    def device_count(self):
        return len(self._devices)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, list):
            # per-device feed dicts: concatenate along batch
            merged: Dict[str, np.ndarray] = {}
            for d in feed:
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(v, 0) for k, v in merged.items()}
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        feed_arrays = {}
        gb = self._program.global_block()
        for k, v in feed.items():
            arr = np.asarray(v)
            if gb._has_var_recursive(k):
                want = core.np_dtype(gb._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            feed_arrays[k] = arr

        key = (id(self._program), self._program._version, tuple(fetch_names),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())))
        entry = self._cache.get(key)
        if entry is None:
            plan = BlockPlan(self._program, 0, list(feed_arrays), fetch_names)
            fn = self._build(plan)
            entry = (plan, fn)
            self._cache[key] = entry
        plan, fn = entry

        batch_spec = NamedSharding(self._mesh, P("dp"))
        repl = NamedSharding(self._mesh, P())
        feed_dev = {k: jax.device_put(v, batch_spec)
                    for k, v in feed_arrays.items()}
        state_vals = {}
        for name in plan.state_in:
            val = self._scope.get(name, _MISSING)
            if val is _MISSING:
                if gb._has_var_recursive(name) and \
                        gb._var_recursive(name).is_data:
                    raise RuntimeError(f"Data variable '{name}' was not fed")
                raise RuntimeError(f"Variable '{name}' is not initialized; "
                                   f"run the startup program first")
            state_vals[name] = jax.device_put(val, repl)
        if plan.needs_rng:
            rk = self._scope.get(RNG_STATE_VAR, _MISSING)
            if rk is _MISSING:
                rk = jax.random.PRNGKey(self._program.random_seed or 0)
            state_vals[RNG_STATE_VAR] = jax.device_put(rk, repl)

        fetches, new_state = fn(feed_dev, state_vals)
        for name, val in new_state.items():
            self._scope.set(name, val)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    def _build(self, plan):
        program = self._program
        repl = NamedSharding(self._mesh, P())

        def fn(feed_vals, state_vals):
            return trace_block(program, 0, plan, feed_vals, state_vals)

        # state (params/accumulators) stays replicated; feeds arrive sharded
        # on the batch dim; XLA SPMD inserts gradient all-reduces.
        return jax.jit(fn, out_shardings=(None, repl))

    def bcast_params(self):
        """ref: parallel_executor.cc:234 BCastParamsToDevices — replication is
        expressed via sharding; nothing to broadcast eagerly."""
        return None
