"""append_backward: IR-level reverse-mode autodiff (ref: python/paddle/fluid/
backward.py:469, grad accumulation :135, op-path search :645).

The backward graph is materialized as ``<type>_grad`` ops inside the Program —
same contract as the reference, so transpilers/parallel passes can inspect and
rewrite it.  Unlike the reference there is no per-op C++ GradOpDescMaker: the
grad op's *descriptor* is generated uniformly (forward inputs + forward
outputs + output-grads in; input-grads out) and its *kernel* is jax.vjp over
the forward impl (ops/registry.py), with explicit overrides where needed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from .framework import GRAD_VAR_SUFFIX, OpRole, Program, Variable, grad_var_name
from ..ops import registry as _reg


def _find_relevant_ops(block, loss_name: str):
    """Ops (by index) whose outputs transitively feed the loss."""
    needed: Set[str] = {loss_name}
    relevant = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(n in needed for n in op.output_arg_names):
            relevant.append(idx)
            needed.update(n for n in op.input_arg_names if n)
    return list(reversed(relevant))


def _creates_grad(block, name: str, no_grad_set: Set[str]) -> bool:
    if not name or name in no_grad_set:
        return False
    if not block._has_var_recursive(name):
        return False
    return not block._var_recursive(name).stop_gradient


def _ensure_grad_var(block, fwd_name: str, grad_name: str):
    if block.has_var(grad_name):
        return block.var(grad_name)
    if block._has_var_recursive(fwd_name):
        fv = block._var_recursive(fwd_name)
        return block.create_var(name=grad_name, shape=fv.shape, dtype=fv.dtype,
                                persistable=False)
    return block.create_var(name=grad_name, persistable=False)


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None) -> List:
    """Returns [(param, grad_var)] pairs; mutates loss's program in place."""
    program: Program = loss.block.program
    block = program.global_block()
    no_grad = set(no_grad_set or ())
    relevant = _find_relevant_ops(block, loss.name)

    # grad bookkeeping: fwd var name -> list of produced grad var names
    produced: Dict[str, List[str]] = {}

    # seed: d loss / d loss = 1.  The __loss_seed__ tag lets the executor
    # fold a dynamic loss scale (and the guardian's grad-Inf fault
    # injection) into the seed at trace time via the @LOSS_SEED_MUL@ env
    # entry — see executor.run_op and guardian.seed_multiplier.
    loss_grad = grad_var_name(loss.name)
    _ensure_grad_var(block, loss.name, loss_grad)
    block.append_op(
        type="fill_any_like", inputs={"X": [loss.name]},
        outputs={"Out": [loss_grad]},
        attrs={"value": 1.0, "__loss_seed__": True,
               OpRole.KEY: OpRole.Backward | OpRole.Loss})
    produced[loss.name] = [loss_grad]

    def finalize_grad(name: str) -> Optional[str]:
        """Collapse accumulated partial grads for `name` into one var."""
        glist = produced.get(name)
        if not glist:
            return None
        if len(glist) == 1:
            return glist[0]
        out = grad_var_name(name)
        _ensure_grad_var(block, name, out)
        block.append_op(type="sum", inputs={"X": list(glist)},
                        outputs={"Out": [out]},
                        attrs={OpRole.KEY: OpRole.Backward})
        produced[name] = [out]
        return out

    fwd_ops = [(i, block.ops[i]) for i in relevant]
    for i, fop in reversed(fwd_ops):
        # incoming grads for this op's outputs
        out_grad_slots = {}
        has_any = False
        for slot, names in fop.outputs.items():
            gnames = []
            for n in names:
                g = finalize_grad(n) if n else None
                gnames.append(g if g is not None else "")
                if g is not None:
                    has_any = True
            out_grad_slots[slot + GRAD_VAR_SUFFIX] = gnames
        if not has_any:
            continue

        # requested input grads
        in_grad_slots = {}
        role_vars = []
        for slot, names in fop.inputs.items():
            gnames = []
            want = False
            for n in names:
                if _creates_grad(block, n, no_grad):
                    prev = produced.setdefault(n, [])
                    gname = grad_var_name(n) if not prev else \
                        f"{grad_var_name(n)}@RENAME@{len(prev)}"
                    prev.append(gname)
                    _ensure_grad_var(block, n, gname)
                    gnames.append(gname)
                    want = True
                else:
                    gnames.append("")
            if want:
                in_grad_slots[slot + GRAD_VAR_SUFFIX] = gnames
        if not in_grad_slots:
            continue

        gtype = fop.type + "_grad"
        inputs = {slot: list(names) for slot, names in fop.inputs.items()}
        for slot, names in fop.outputs.items():
            inputs[slot] = list(names)
        inputs.update(out_grad_slots)
        # __fwd_op_idx__ links the grad op to its forward op so the executor
        # can replay the forward's *host* inputs (loop counters mutated
        # in-place between forward and backward — e.g. array indices)
        gop = block.append_op(type=gtype, inputs=inputs, outputs=in_grad_slots,
                              attrs=dict(fop.attrs,
                                         **{OpRole.KEY: OpRole.Backward,
                                            "__fwd_op_idx__": i}))
        if callbacks:
            for cb in callbacks:
                cb(block=block, context={"__current_op_desc__": gop})

    # finalize param grads
    if parameter_list is not None:
        params = [block._var_recursive(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = block.all_parameters()

    params_grads = []
    for p in params:
        if not getattr(p, "trainable", True):
            continue
        g = finalize_grad(p.name)
        if g is None:
            continue
        gvar = block.var(g)
        params_grads.append((p, gvar))

    # tag (param, grad) pairs on backward ops for the parallel pass/transpiler
    pg_names = {g.name: p.name for p, g in params_grads}
    for op in block.ops:
        if op.attr(OpRole.KEY, 0) & OpRole.Backward:
            rv = []
            for n in op.output_arg_names:
                if n in pg_names:
                    rv += [pg_names[n], n]
            if rv:
                op.attrs[OpRole.VAR_KEY] = rv

    program._params_grads = params_grads
    # the guardian's numerics sentinel needs to know which var IS the loss
    program._loss_name = loss.name
    return params_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of targets w.r.t. inputs (ref: backward.py:685)."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    if len(targets) != 1:
        raise NotImplementedError("calc_gradient supports a single target for now")
    t = targets[0]
    block = t.block
    saved = {v.name: v.stop_gradient for v in inputs}
    for v in inputs:
        v.stop_gradient = False
    try:
        append_backward(t, parameter_list=None, no_grad_set=no_grad_set)
    finally:
        for v in inputs:
            v.stop_gradient = saved[v.name]
    outs = []
    for v in inputs:
        gname = grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs
