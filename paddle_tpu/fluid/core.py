"""Core runtime primitives: places, dtypes, device resolution.

TPU-native analogue of the reference's ``paddle/fluid/platform/place.h`` and the
pybind ``core`` module (ref: pybind/pybind.cc:443-455).  Instead of a C++
``boost::variant<CUDAPlace, CPUPlace, ...>`` dispatching to per-device kernels,
a Place here selects a JAX/PJRT device set; all compute lowers to XLA.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------


class VarType:
    """Mirror of the reference's framework.proto VarType (framework.proto:104).

    Values are stable small ints so programs can be serialized.
    """

    BOOL = 0
    INT16 = 1
    INT32 = 2
    INT64 = 3
    FP16 = 4
    FP32 = 5
    FP64 = 6
    UINT8 = 7
    INT8 = 8
    BF16 = 9
    # non-pod types
    LOD_TENSOR = 20
    SELECTED_ROWS = 21
    FEED_MINIBATCH = 22
    FETCH_LIST = 23
    STEP_SCOPES = 24
    LOD_RANK_TABLE = 25
    LOD_TENSOR_ARRAY = 26
    READER = 28
    RAW = 30


_STR_TO_NP = {
    "bool": np.bool_,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "float16": np.float16,
    "float32": np.float32,
    "float64": np.float64,
    "uint8": np.uint8,
    "int8": np.int8,
    # bfloat16 resolved lazily through ml_dtypes (always present with jax)
}

_STR_TO_VARTYPE = {
    "bool": VarType.BOOL,
    "int16": VarType.INT16,
    "int32": VarType.INT32,
    "int64": VarType.INT64,
    "float16": VarType.FP16,
    "float32": VarType.FP32,
    "float64": VarType.FP64,
    "uint8": VarType.UINT8,
    "int8": VarType.INT8,
    "bfloat16": VarType.BF16,
}

_VARTYPE_TO_STR = {v: k for k, v in _STR_TO_VARTYPE.items()}


def convert_dtype(dtype) -> str:
    """Normalize any dtype spec (string, numpy dtype, VarType int) to a string."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        if dtype in _STR_TO_VARTYPE:
            return dtype
        # allow numpy-style names like "float" / "double"
        return np.dtype(dtype).name
    if isinstance(dtype, int):
        if dtype in _VARTYPE_TO_STR:
            return _VARTYPE_TO_STR[dtype]
        raise ValueError(f"unknown VarType enum {dtype}")
    try:
        name = np.dtype(dtype).name
        if name in _STR_TO_VARTYPE:
            return name
    except TypeError:
        pass
    # ml_dtypes bfloat16 etc.
    name = getattr(dtype, "name", None) or str(dtype)
    if name in _STR_TO_VARTYPE:
        return name
    raise ValueError(f"cannot convert dtype {dtype!r}")


def np_dtype(dtype) -> np.dtype:
    name = convert_dtype(dtype)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_STR_TO_NP[name])


# ---------------------------------------------------------------------------
# Places
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Place:
    device_type: str  # "cpu" | "tpu" | "gpu"
    device_id: int = 0

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"{self.device_type.upper()}Place({self.device_id})"


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(Place):
    """Accepted for API parity; resolves to whatever accelerator JAX has."""

    def __init__(self, device_id: int = 0):
        super().__init__("gpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


def _jax():
    import jax

    return jax


def get_jax_device(place: Place):
    """Resolve a Place to a concrete jax.Device (best effort).

    Always a process-LOCAL device: under jax.distributed the global device
    list starts with process 0's devices, and committing feeds to another
    process's device would make every fetch non-addressable here (the
    local-SGD runner hit exactly that)."""
    jax = _jax()
    kind = place.device_type

    def local(k):
        return [d for d in jax.local_devices() if d.platform == k]

    if kind == "cpu":
        devs = local("cpu") or jax.devices("cpu")
    else:
        # tpu / gpu: take the default backend's devices; on a TPU host this is
        # the TPU chip, under forced-CPU tests it degrades to host devices.
        try:
            devs = local(kind) or jax.devices(kind)
        except RuntimeError:
            devs = jax.local_devices()
    return devs[place.device_id % len(devs)]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    try:
        return any(d.platform == "tpu" for d in _jax().devices())
    except RuntimeError:  # pragma: no cover
        return False


def get_device_count(kind: str = None) -> int:
    jax = _jax()
    try:
        return len(jax.devices(kind)) if kind else len(jax.devices())
    except RuntimeError:
        return 0


# gflags-style runtime flags (ref: python/paddle/fluid/__init__.py:121-140
# imports gflags from env via core.init_gflags, pybind.cc:517 InitGflags).
# A plain dict; init_gflags supports the reference's two arg forms:
# "--tryfromenv=a,b,c" (import FLAGS_<name> from the environment) and
# direct "--name=value" assignment.
def _flag_value(raw):
    """Parse a flag's textual value preserving its type: numerics stay
    numeric ('1' -> 1, not True — gflags int flags like --rpc_retry_times=1
    must survive round-trips), only true/false-style literals become bools,
    and anything else stays a string (so a flag legitimately valued 'on'
    would be the bool True but e.g. 'ON_DEMAND' stays text)."""
    if isinstance(raw, bool):
        return raw
    s = str(raw).strip()
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    if s.lower() in ("true", "yes", "on"):
        return True
    if s.lower() in ("false", "no", "off", ""):
        return False
    return s


GLOBAL_FLAGS = {
    "check_nan_inf": _flag_value(os.environ.get("FLAGS_check_nan_inf", "0")),
    "benchmark": _flag_value(os.environ.get("FLAGS_benchmark", "0")),
}


def init_gflags(args=None):
    """ref: platform/init.cc:36 InitGflags via pybind.cc:517."""
    for arg in (args or []):
        if not isinstance(arg, str) or not arg.startswith("--"):
            continue
        body = arg[2:]
        if body.startswith("tryfromenv="):
            for name in body[len("tryfromenv="):].split(","):
                name = name.strip()
                if not name:
                    continue
                env = os.environ.get(f"FLAGS_{name}")
                if env is not None:
                    GLOBAL_FLAGS[name] = _flag_value(env)
        elif "=" in body:
            name, _, val = body.partition("=")
            GLOBAL_FLAGS[name.strip()] = _flag_value(val)
    return True


def init_devices():
    return True


class EOFException(Exception):
    """Raised when a reader's queue is exhausted (ref: the C++ executor
    throws EOFException from the read op; users catch fluid.core.
    EOFException around their train loop)."""


# host-side LoDTensor lives in fluid.lod_tensor; re-export for the pybind
# parity surface (ref exposes core.LoDTensor, pybind.cc:160)
from .lod_tensor import LoDTensor  # noqa: E402,F401


def __getattr__(attr):
    # ref pybind.cc:345 exposes core.Scope; ours lives in fluid.executor
    # (imported lazily here — executor imports core at module load)
    if attr == "Scope":
        from .executor import Scope

        return Scope
    raise AttributeError(attr)
