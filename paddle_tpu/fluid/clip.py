"""Gradient / error clipping (ref: python/paddle/fluid/clip.py — ErrorClip,
ClipByValue, ClipByNorm, ClipByGlobalNorm :212)."""

from __future__ import annotations

import functools

from .framework import OpRole, default_main_program

__all__ = ["ErrorClipByValue", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "append_gradient_clip_ops",
           "append_unscale_ops", "error_clip_callback", "set_gradient_clip"]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        block.append_op(type="clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max,
                               OpRole.KEY: OpRole.Backward})


def error_clip_callback(block, context):
    op = context["__current_op_desc__"]
    for grad_n in op.output_arg_names:
        if not grad_n.endswith("@GRAD"):
            continue
        fwd_var_name = grad_n[: -len("@GRAD")]
        if not block._has_var_recursive(fwd_var_name):
            continue
        fwd_var = block._var_recursive(fwd_var_name)
        error_clip = getattr(fwd_var, "error_clip", None)
        if error_clip is not None:
            error_clip._append_clip_op(block, grad_n)


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        raise NotImplementedError

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        from .layers import nn as _nn

        new_grad = _nn.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        from .layers import nn as _nn

        new_grad = _nn.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        elif context[self.group_name + "_clip_value"] != self.clip_norm:
            raise ValueError("all parameters in a group should share clip_norm")
        from .layers import nn as _nn

        local_norm = _nn.reduce_sum(_nn.elementwise_mul(grad, grad))
        context[self.group_name].append(local_norm)
        self.context = context

    def _create_operators(self, param, grad):
        from .layers import nn as _nn, ops as _ops, tensor as _tensor

        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = _tensor.sums(input=self.context[self.group_name])
            group_norm = _ops.sqrt(group_norm)
            clip_var = _tensor.fill_constant(shape=[1], dtype="float32",
                                             value=self.clip_norm)
            group_scale = _nn.elementwise_div(
                clip_var, _nn.elementwise_max(clip_var, group_norm))
            self.context[group_scale_name] = group_scale
        new_grad = _nn.elementwise_mul(grad, self.context[group_scale_name])
        return param, new_grad


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [program.global_block()._var_recursive(p) if isinstance(p, str)
                  else p for p in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_unscale_ops(params_grads, loss_scale_var):
    """Divide every raw grad by the dynamic loss scale (fluid.amp fp16
    training).  Sits between append_backward and the clip ops, so norms
    and clip thresholds see TRUE gradient magnitudes — the scale only
    ever exists inside the backward pass.  Returns fresh (param, grad)
    pairs; the raw (scaled) grads stay in ``program._params_grads``,
    which is exactly what the guardian's overflow check wants to see."""
    from .framework import program_guard
    from .layers import nn as _nn

    res = []
    for p, g in params_grads:
        if g is None:
            res.append((p, g))
            continue
        block = p.block
        with program_guard(block.program):
            new_grad = _nn.elementwise_div(g, loss_scale_var)
        # backward role: for_test clones and inference pruning must drop
        # the unscale ops together with the rest of the backward graph
        block.ops[-1].attrs[OpRole.KEY] = OpRole.Backward
        res.append((p, new_grad))
    return res


def append_gradient_clip_ops(param_grad):
    context = {}
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or \
            NullGradientClipAttr()
        clip_attr._process_context(context=context, param=p, grad=g)
    res = []
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or \
            NullGradientClipAttr()
        res.append(clip_attr._create_operators(param=p, grad=g))
    return res
