"""Executor: runs a Program on a Place by tracing it into one XLA computation.

The reference's Executor is a per-op C++ interpreter (ref: executor.cc:129,
hot loop :354 ``for op in ctx->ops_: op->Run(scope, place)``) — every op is a
separate kernel launch.  On TPU that model wastes the machine: the idiomatic
design is to trace the *whole block* into a single jitted function
(feed, state) -> (fetches, new_state) and let XLA fuse/schedule it.  The Scope
survives as the host-side name->buffer table holding persistable state
(parameters, optimizer accumulators, RNG key) between runs.

Mutation semantics (SURVEY.md hard part #2): Fluid ops mutate scope vars in
place (sgd writes ParamOut into the Param var).  Tracing SSA-ifies this by
rebinding names in a trace-time environment; vars that were read from the
scope and rewritten become donated inputs / fresh outputs of the XLA program,
so XLA can alias their buffers (true in-place update on TPU HBM).
"""

from __future__ import annotations

import os
import warnings
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import core
from .framework import (OpRole, Program, RNG_STATE_VAR, Variable,
                        default_main_program)
from ..ops import registry as _reg


# ---------------------------------------------------------------------------
# Scope (ref: scope.h:41 — hierarchical name->Variable map)
# ---------------------------------------------------------------------------


class _ScopeTensor:
    """Minimal LoDTensor-view over a scope entry, for API parity
    (supports np.array(t), t.set(arr, place), t.shape)."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def __array__(self, dtype=None):
        v = self._scope._values[self._name]
        if v is _UNINIT:
            raise ValueError(
                f"Variable '{self._name}' exists in the scope but holds no "
                f"tensor yet (created via Scope.var but never set — the "
                f"reference faults the same way on an uninitialized var)")
        a = np.asarray(v)
        return a.astype(dtype) if dtype is not None else a

    def set(self, array, place=None):
        self._scope._values[self._name] = np.asarray(array)

    @property
    def shape(self):
        v = self._scope._values[self._name]
        if v is _UNINIT:
            raise ValueError(
                f"Variable '{self._name}' holds no tensor yet")
        return tuple(v.shape)

    def recursive_sequence_lengths(self):
        # scope._lods stores offsets form; convert at the API surface
        from .lod_tensor import _offsets_to_lengths

        off = self._scope._lods.get(self._name) or ()
        return [_offsets_to_lengths(level) for level in off]

    def set_recursive_sequence_lengths(self, lengths):
        from .lod_tensor import _lengths_to_offsets

        self._scope._lods[self._name] = tuple(
            _lengths_to_offsets(l) for l in lengths)

    def lod(self):
        return self._scope._lods.get(self._name) or ()

    def set_lod(self, lod):
        self._scope._lods[self._name] = tuple(
            tuple(int(x) for x in level) for level in lod)


class _ScopeVar:
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return _ScopeTensor(self._scope, self._name)


class Scope:
    """name -> value table; values are host numpy or device jax arrays."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._values: Dict[str, object] = {}
        self._lods: Dict[str, list] = {}
        self._parent = parent
        self._kids: List[Scope] = []

    def var(self, name) -> _ScopeVar:
        # creation API (ref scope.h Scope::Var creates an UNINITIALIZED
        # Variable): the slot exists but reads fault until set() — a
        # misspelled var name must not silently read zeros
        if name not in self._values:
            self._values[name] = _UNINIT
        return _ScopeVar(self, name)

    def find_var(self, name) -> Optional[_ScopeVar]:
        s = self
        while s is not None:
            if name in s._values:
                return _ScopeVar(s, name)
            s = s._parent
        return None

    def new_scope(self) -> "Scope":
        k = Scope(self)
        self._kids.append(k)
        return k

    def drop_kids(self):
        self._kids.clear()

    # -- internal fast path --
    def get(self, name, default=None):
        s = self
        while s is not None:
            if name in s._values:
                v = s._values[name]
                return default if v is _UNINIT else v
            s = s._parent
        return default

    def set(self, name, value):
        self._values[name] = value

    def has(self, name) -> bool:
        return self.get(name, _MISSING) is not _MISSING

    def keys(self):
        return self._values.keys()


_MISSING = object()
_UNINIT = object()
_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()


# ---------------------------------------------------------------------------
# Block tracing
# ---------------------------------------------------------------------------


_SIDE_EFFECT_OPS = frozenset(["print", "save", "save_combine"])


class BlockPlan:
    """Static analysis of a block: which ops are live for the requested
    fetches (dead ops are pruned — XLA would DCE them anyway, but pruning
    first avoids demanding un-fed inputs), which names come from scope
    (state_in), which persistables are (re)written (state_out)."""

    def __init__(self, program: Program, block_idx: int,
                 feed_names: Sequence[str], fetch_names: Sequence[str]):
        block = program.block(block_idx)
        self.block = block
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)

        def _is_persistable(name: str) -> bool:
            return block._has_var_recursive(name) and \
                block._var_recursive(name).persistable

        # 1. live-op slice: keep ops needed for fetches or persistable updates
        needed = set(fetch_names)
        kept = []
        for op in reversed(block.ops):
            if op.type in _SKIP_OPS:
                continue
            outs = [n for n in op.output_arg_names if n]
            live = (op.type in _SIDE_EFFECT_OPS
                    or any(n in needed for n in outs)
                    or any(_is_persistable(n) for n in outs))
            if not live:
                continue
            kept.append(op)
            needed.update(n for n in op.input_arg_names if n)
        self.ops = list(reversed(kept))

        # 2. dataflow analysis over the kept ops
        written = set(feed_names)
        state_in: List[str] = []
        self.needs_rng = False
        self.needs_eager = False

        def _scan_rng(op):
            d = _resolve_opdef(op.type)
            if d is not None and d.stateful:
                self.needs_rng = True
            sub = op.attr("sub_block") if hasattr(op, "attr") else None
            if isinstance(sub, int):
                for bop in program.block(sub).ops:
                    _scan_rng(bop)

        def _op_is_eager(op) -> bool:
            """Data-dependent op (or control flow containing one) — must run
            outside jit."""
            from ..ops.array_ops import EAGER_OPS

            base = op.type[:-5] if op.type.endswith("_grad") else op.type
            if base in EAGER_OPS:
                return True
            sub = op.attr("sub_block") if hasattr(op, "attr") else None
            if isinstance(sub, int):
                return any(_op_is_eager(b) for b in program.block(sub).ops)
            return False

        for op in self.ops:
            _scan_rng(op)

        # eager-island segmentation (SURVEY.md §7 hard part #1): contiguous
        # runs of traceable ops become jittable segments; only the
        # data-dependent islands between them run eagerly.  A beam-search
        # decode program keeps its whole encoder in one compiled segment.
        self.segments: List[Tuple[str, list]] = []
        for op in self.ops:
            kind = "eager" if _op_is_eager(op) else "jit"
            if self.segments and self.segments[-1][0] == kind:
                self.segments[-1][1].append(op)
            else:
                self.segments.append((kind, [op]))
        self.needs_eager = any(k == "eager" for k, _ in self.segments)
        for op in self.ops:
            for name in op.input_arg_names:
                if not name:
                    continue
                if name not in written and name not in state_in:
                    state_in.append(name)
            for name in op.output_arg_names:
                if name:
                    written.add(name)
        state_out: List[str] = []
        for op in self.ops:
            for name in op.output_arg_names:
                if not name or name in state_out:
                    continue
                if name in state_in or _is_persistable(name):
                    state_out.append(name)
        # fetches that are never produced in-block must come from state
        for name in self.fetch_names:
            if name not in written and name not in state_in:
                state_in.append(name)
        self.state_in = state_in
        self.state_out = state_out


def _resolve_opdef(op_type):
    if _reg.is_registered(op_type):
        return _reg.get_op_def(op_type)
    if op_type.endswith("_grad") and _reg.is_registered(op_type[:-5]):
        return _reg.get_op_def(op_type[:-5])
    return None


_SKIP_OPS = frozenset(["feed", "fetch", "read", "create_py_reader"])


def build_window_fn(program: Program, plan: "BlockPlan", guard, n_user: int,
                    n_steps: int, feed_per_step: bool,
                    trace=None, finalize=None):
    """Build the fused-window step function ``kfn(feed_vals, const_state,
    mut_state, sentinel)`` — a ``lax.scan`` over the traced step with the
    mutable state (plus, when guarded, the aggregated health record) riding
    the carry.  Shared by ``Executor.run_steps`` (single device) and the
    SPMD window runner (``parallel.spmd.ShardedWindowRunner``), so the
    sharded path scans the EXACT same body the single-device oracle tests
    pin down.

    ``trace(feed, state)`` overrides the default ``trace_block`` call
    (the sharded runner wraps it in a ``mesh_scope``); ``finalize(last,
    mut_final, agg)`` post-processes the outputs inside the trace (the
    sharded runner pins shardings there; ``agg`` is None unguarded).
    """
    import jax.numpy as _jnp
    from jax import lax as _lax

    from . import guardian as _guardian

    if trace is None:
        def trace(feed_vals, state_vals):
            return trace_block(program, 0, plan, feed_vals, state_vals)
    if finalize is None:
        def finalize(last, mut_final, agg):
            return last, mut_final, agg

    def kfn(feed_vals, const_state, mut_state, sentinel):
        def body(carry, xs):
            if guard is not None:
                mut, _prev_fetch, agg = carry
            else:
                mut, _prev_fetch = carry
            step_feed = dict(xs["feed"] if feed_per_step
                             else feed_vals)
            state = dict(const_state)
            state.update(mut)
            if guard is not None:
                step_sent = {"loss_cap": sentinel["loss_cap"],
                             "seed_mul": xs["seed_mul"],
                             "loss_mul": xs["loss_mul"]}
                step_feed[_guardian.LOSS_SEED_MUL] = \
                    _guardian.seed_multiplier(guard, state, step_sent)
            fetches, new_state = trace(step_feed, state)
            # fetches ride the carry: only the LAST step's values
            # survive, with no (n_steps, ...) stacking buffer
            if guard is not None:
                committed, health = _guardian.fold_health(
                    guard, fetches[n_user:], new_state, mut, state,
                    step_sent)
                agg = _guardian.window_health_update(
                    agg, health, xs["i"], n_steps)
                return ({**mut, **committed}, fetches[:n_user],
                        agg), None
            return ({**mut, **new_state}, fetches), None

        first_feed = (
            {k: v[0] for k, v in feed_vals.items()}
            if feed_per_step else feed_vals)
        fetch0, state0 = jax.eval_shape(
            lambda st: trace(first_feed, {**const_state, **st}),
            mut_state)
        fetch0 = [_jnp.zeros(t.shape, t.dtype)
                  for t in fetch0[:n_user]]
        # write-only persistables (written before first read, e.g.
        # a decayed lr var) appear in new_state but not in
        # _gather_state's mut_state — seed them so the carry
        # structure is stable across scan iterations
        mut_state = dict(mut_state)
        for k, t in state0.items():
            if k not in mut_state:
                mut_state[k] = _jnp.zeros(t.shape, t.dtype)
        xs = {"i": _jnp.arange(n_steps, dtype=_jnp.int32)}
        if feed_per_step:
            xs["feed"] = feed_vals
        if guard is not None:
            xs["seed_mul"] = sentinel["seed_mul"]
            xs["loss_mul"] = sentinel["loss_mul"]
            carry0 = (mut_state, fetch0,
                      _guardian.window_health_init(n_steps))
            (mut_final, last, agg), _ = _lax.scan(
                body, carry0, xs, length=n_steps)
            last, mut_final, agg = finalize(last, mut_final, agg)
            return last, mut_final, agg
        (mut_final, last), _ = _lax.scan(
            body, (mut_state, fetch0), xs, length=n_steps)
        last, mut_final, _ = finalize(last, mut_final, None)
        return last, mut_final

    return kfn


LOD_SUFFIX = "@LOD"


def trace_block(program: Program, block_idx: int, plan: BlockPlan,
                feed_vals: Dict[str, jnp.ndarray],
                state_vals: Dict[str, jnp.ndarray],
                static_env: Optional[Dict[str, object]] = None,
                lod_box: Optional[Dict[str, object]] = None):
    """Run every op in the block symbolically; returns (fetches, new_state).

    ``static_env`` carries compile-time-constant entries — notably
    ``<name>@LOD`` sequence metadata (tuples of offset tuples).  LoD is
    *static* in this framework (SURVEY.md §5.7: the TPU answer to variable
    length is bucketing + segment ids, not dynamic shapes): packed sequence
    data keeps a static [sum_len, ...] shape and the offsets are baked into
    the trace, so XLA sees fully static programs.  ``lod_box``, if given,
    receives the lod of every fetch/state name produced by the trace.
    """
    env: Dict[str, object] = {}
    if static_env:
        env.update(static_env)
    env.update(state_vals)
    env.update(feed_vals)
    rng_box = None
    if plan.needs_rng:
        rng_box = [state_vals[RNG_STATE_VAR]]
    for op in plan.ops:
        run_op(op, env, rng_box)
    fetches = [env[n] for n in plan.fetch_names]
    new_state = {n: env[n] for n in plan.state_out if n in env}
    if rng_box is not None:
        new_state[RNG_STATE_VAR] = rng_box[0]
    if lod_box is not None:
        for n in list(plan.fetch_names) + list(plan.state_out):
            lod = env.get(n + LOD_SUFFIX)
            if lod is not None:
                lod_box[n] = lod
    return fetches, new_state


def run_op(op, env: Dict[str, object], rng_box=None):
    """Execute one IR op against a trace environment."""
    from . import control_flow_exec

    if op.type in control_flow_exec.HANDLERS:
        control_flow_exec.HANDLERS[op.type](op, env, rng_box, run_op)
        return

    is_grad = (not _reg.is_registered(op.type)) and op.type.endswith("_grad") \
        and _reg.is_registered(op.type[:-5])
    opdef = _reg.get_op_def(op.type[:-5] if is_grad else op.type)

    inputs = {}
    for slot, names in op.inputs.items():
        inputs[slot] = [env.get(n) if n else None for n in names]
        # companion static LoD entries (sequence metadata; see trace_block)
        lods = [env.get(n + LOD_SUFFIX) if n else None for n in names]
        if any(l is not None for l in lods):
            inputs[slot + LOD_SUFFIX] = lods
    # current values of in-out outputs (tensor arrays accumulate)
    for slot, names in op.outputs.items():
        cur = [env.get(n) if n else None for n in names]
        if any(c is not None for c in cur):
            inputs[slot + "@CURRENT"] = cur

    # host inputs (loop counters, array indices) mutate in place between
    # forward and backward; forward ops stash theirs so the matching grad op
    # (linked via __fwd_op_idx__, see backward.py) replays the values it
    # actually saw
    if is_grad:
        fwd_idx = op.attr("__fwd_op_idx__")
        if fwd_idx is not None and fwd_idx < len(op.block.ops):
            stash = env.get("@FWD_HOST@", {}).get(
                id(op.block.ops[fwd_idx]))
            if stash:
                inputs.update(stash)
    else:
        host_slots = {
            slot: vals for slot, vals in inputs.items()
            if not slot.endswith(LOD_SUFFIX)
            and any(isinstance(v, np.ndarray) for v in vals)}
        if host_slots:
            env.setdefault("@FWD_HOST@", {})[id(op)] = {
                s: list(v) for s, v in host_slots.items()}
    outputs_spec = {slot: list(names) for slot, names in op.outputs.items() if names}
    ctx = _reg.ExecContext(op.type, inputs, outputs_spec, op.attrs, rng_box)

    # the scope name lands in XLA HLO metadata (op_name="jit(..)/<type>/..")
    # so device profiles attribute per-HLO-op time back to framework ops
    # (ref: platform/device_tracer.h:49 correlation_id -> op role; here the
    # correlation is carried by the compiler instead of CUPTI ids)
    with jax.named_scope(op.type):
        if is_grad:
            if opdef.grad_fn is not None:
                raw = opdef.grad_fn(ctx)
            else:
                raw = _reg.run_grad_generic(opdef, ctx)
        else:
            raw = opdef.fn(ctx)

    # split off "<slot>@LOD" returns (each a list of lods parallel to the
    # slot's output names) before array normalization
    out_lods = {}
    if raw:
        for k in [k for k in raw if k.endswith(LOD_SUFFIX)]:
            v = raw.pop(k)
            out_lods[k[: -len(LOD_SUFFIX)]] = v if isinstance(v, list) else [v]
    outs = _reg._normalize_outputs(raw)

    # default ShareLoD (ref: ops declare ShareLoD in InferShape; here a
    # guarded heuristic): a unique input lod propagates to any output whose
    # leading dim still equals the packed row count
    share_lod = None
    in_lods = {tuple(map(tuple, l))
               for k, ls in inputs.items() if k.endswith(LOD_SUFFIX)
               for l in ls if l is not None}
    if len(in_lods) == 1:
        share_lod = next(iter(in_lods))

    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        lods = out_lods.get(slot)
        for i, name in enumerate(names):
            if not name:
                continue
            if vals is not None and i < len(vals) and vals[i] is not None:
                env[name] = vals[i]
                # rebinding a var invalidates any previous LoD; it is
                # re-attached below only if this op declares/shares one
                env.pop(name + LOD_SUFFIX, None)
                if (lods is None or i >= len(lods)) and share_lod is not None \
                        and getattr(vals[i], "shape", None) \
                        and vals[i].shape[0] == share_lod[-1][-1]:
                    env[name + LOD_SUFFIX] = share_lod
            if lods is not None and i < len(lods) and lods[i] is not None:
                env[name + LOD_SUFFIX] = tuple(tuple(l) for l in lods[i])

    # backward-seed scaling (dynamic fp16 loss scale and/or the guardian's
    # grad-Inf fault injection): the op append_backward tagged __loss_seed__
    # has its output multiplied by the traced @LOSS_SEED_MUL@ scalar the
    # guarded step placed in the env.  One dict lookup for every other op.
    if "__loss_seed__" in op.attrs:
        mul = env.get(_guardian_mod().LOSS_SEED_MUL)
        if mul is not None:
            for names in op.outputs.values():
                for n in names:
                    if n and n in env:
                        env[n] = env[n] * jnp.asarray(mul, env[n].dtype)


def _guardian_mod():
    from . import guardian

    return guardian


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class _JitCache:
    """Bounded in-process jit cache (LRU by last use).

    The old dict grew without bound across programs — a long-lived process
    cycling many Programs (serving several models, notebooks, the test
    suite) pinned every compiled executable plus its donated-buffer
    metadata forever.  ``PADDLE_EXECUTOR_CACHE_CAP`` bounds it (default
    64 entries, comfortably above any serving bucket set); size and
    evictions surface as always-on profiler counters."""

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            from . import envcontract

            cap = envcontract.get("PADDLE_EXECUTOR_CACHE_CAP")
        self.cap = max(1, int(cap))
        self.evictions = 0
        self._od: "OrderedDict[tuple, tuple]" = OrderedDict()

    def get(self, key):
        entry = self._od.get(key)
        if entry is not None:
            self._od.move_to_end(key)
        return entry

    def __setitem__(self, key, entry):
        from . import profiler as _prof

        self._od[key] = entry
        self._od.move_to_end(key)
        while len(self._od) > self.cap:
            self._od.popitem(last=False)
            self.evictions += 1
            _prof.record_counter("executor.jit_cache.evictions")
        _prof.record_counter("executor.jit_cache.size",
                             value=len(self._od))

    def __len__(self):
        return len(self._od)

    def __contains__(self, key):
        return key in self._od

    def clear(self):
        self._od.clear()


class Executor:
    """ref: python/paddle/fluid/executor.py:256.  ``place`` selects the JAX
    device; everything else is handled by XLA."""

    def __init__(self, place=None):
        self.place = place if place is not None else core.CPUPlace()
        self._cache = _JitCache()
        # feed-name -> (host snapshot, device buffer): unchanged feeds are
        # NOT re-shipped every step.  On a tunneled/remote TPU the H2D copy
        # dominates step time for repeated feeds, so this cache is the
        # difference between transfer-bound and compute-bound training.
        self._feed_cache = {}

    def close(self):
        self._cache.clear()
        self._feed_cache.clear()

    def run_steps(self, program, feed, fetch_list, n_steps,
                  scope=None, feed_per_step=False):
        """Run ``n_steps`` training steps inside ONE device dispatch.

        A ``lax.scan`` over the traced step with the mutable state as the
        (donated) carry — the standard TPU host-loop amortization: per-step
        dispatch latency vanishes, parameters never leave the device, and
        XLA pipelines step k+1's compute behind step k.  On a tunneled
        transport with a multi-ms per-dispatch floor this is the difference
        between dispatch-bound and compute-bound training (the analogue of
        the reference's `--use_reader_op` in-graph data loop, ref
        benchmark/fluid/fluid_benchmark.py:149 + read op).

        ``feed_per_step=False``: every step consumes the same feed dict
        (synthetic-data benchmarking, ref --use_fake_data).
        ``feed_per_step=True``: each feed array carries a leading
        ``n_steps`` dim and step i consumes slice i.

        Guardian-gated and dynamic-fp16-loss-scaled programs scan too: the
        per-step sentinel (health reduction + ``where(ok)`` commit gate)
        and the loss-scale update ride the carry, and the host observes ONE
        aggregated health record per window (first-trip step index + worst
        values) with the usual one-boundary lag — policy applies at window
        granularity, and a dump bundle captures the PRE-WINDOW state so
        replay reproduces the trip (guardian.replay walks the window).

        Returns the fetches of the LAST step (host numpy).  Programs with
        data-dependent eager islands cannot be scanned and raise.
        """
        program = program or default_main_program()
        scope = scope or global_scope()
        n_steps = int(n_steps)
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list or []]
        feed_arrays = {}
        for k, v in dict(feed or {}).items():
            arr, _lod = self._coerce_feed(program, k, v)
            if _lod:
                raise RuntimeError(
                    "run_steps: LoD feeds are not supported in the "
                    "scanned loop; use Executor.run per step")
            feed_arrays[k] = arr
        from . import amp as _amp
        from . import guardian as _guardian

        # guarded window: sentinel + dynamic loss scale fold into the scan
        # body exactly like Executor.run's single guarded step
        guard = _guardian.for_program(program)
        n_user = len(fetch_names)

        from ..observe import trace as _trace

        key = ("run_steps", program._cache_token, program._version,
               tuple(fetch_names), n_steps, bool(feed_per_step),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())),
               self.place.device_type,
               # execution-mode toggles invalidate compiled fns (same
               # contract as Executor.run's cache key)
               _amp.compute_dtype(),
               guard.cache_token() if guard is not None else None,
               os.environ.get("PADDLE_TPU_FLASH", ""),
               os.environ.get("PADDLE_TPU_FUSED", ""))
        entry = self._cache.get(key)
        probe = None
        fresh_entry = entry is None
        if entry is None:
            import time as _t

            from .log import VLOG
            from .. import analysis as _analysis
            from .. import compile_cache as _cc
            from ..observe import goodput as _goodput

            t_trace0 = _t.perf_counter()
            with _trace.span("executor.trace", n_steps=n_steps):
                # pre-compile verifier (PADDLE_TPU_VERIFY): milliseconds of
                # static checks before seconds of trace/compile; strict mode
                # raises VerifyError here, before any backend work.  Stacked
                # per-step feeds verify as ONE step's slice.
                _analysis.check_before_compile(
                    program,
                    feed=({k: v[0] if getattr(v, "ndim", 0) > 0 else v
                           for k, v in feed_arrays.items()}
                          if feed_per_step else feed_arrays),
                    fetch_list=fetch_names, kind="run_steps")
                # persistent-cache consult BEFORE tracing: a hit means
                # another process already compiled this exact (program, jit
                # config) — the backend executable loads from the shared
                # disk cache
                probe = _cc.executor_probe(
                    program, feed_arrays, fetch_names,
                    extra={"kind": "run_steps", "n_steps": n_steps,
                           "feed_per_step": bool(feed_per_step),
                           "platform": self.place.device_type,
                           "amp": _amp.compute_dtype(),
                           "guard": (guard.cache_token()
                                     if guard is not None else None),
                           "flash": os.environ.get("PADDLE_TPU_FLASH", ""),
                           "fused": os.environ.get("PADDLE_TPU_FUSED", "")})
                VLOG(1, f"Executor.run_steps: compiling {n_steps}-step scan"
                        f"{' (guarded)' if guard is not None else ''}")
                plan_fetches = list(fetch_names)
                if guard is not None:
                    plan_fetches += guard.extra_fetch_names()
                plan = BlockPlan(program, 0, list(feed_arrays), plan_fetches)
                if plan.needs_eager:
                    if guard is not None and guard.scale_vars is not None:
                        raise RuntimeError(
                            "dynamic fp16 loss scaling is not supported for "
                            "programs with data-dependent eager ops")
                    raise RuntimeError(
                        "run_steps: program contains data-dependent eager "
                        "ops; use Executor.run per step")
                if guard is not None and guard.scale_vars:
                    # the scale/good-steps vars are read/written only by the
                    # guarded wrapper (no IR op touches the counter), so
                    # liveness never saw them — gather with the rest of
                    # state
                    for n in guard.scale_vars:
                        if n not in plan.state_in:
                            plan.state_in.append(n)

                kfn = build_window_fn(program, plan, guard, n_user, n_steps,
                                      feed_per_step)
                device = core.get_jax_device(self.place)
                donate = self._donate_argnums(device, program)
                # the trailing dict carries per-entry attribution state
                # (compiled cost analysis, captured lazily under tracing)
                entry = (plan, jax.jit(kfn, donate_argnums=donate), guard,
                         {"cost": None})
                self._cache[key] = entry
            if program._params_grads is not None:
                # host tracing/verification is compile-state wall-clock
                # (the backend compile itself lands in the first dispatch,
                # booked below)
                _goodput.note("compile", _t.perf_counter() - t_trace0)
        plan, fn, guard, entry_info = entry

        import contextlib
        import time as _time

        from . import fault as _fault
        from . import profiler as _prof
        from ..observe import watchdog as _watchdog

        with contextlib.ExitStack() as _tstack:
            # the window span wraps the WHOLE dispatch cycle, so guardian
            # trips / cache probes / slo breaches emitted inside it carry
            # its span id; None (one dict lookup) when tracing is off
            wspan = _tstack.enter_context(
                _trace.span("executor.window", n_steps=n_steps,
                            fresh=fresh_entry))
            t_host0 = _time.perf_counter()
            window_start = 0
            if program._params_grads is not None:
                window_start = self._step_boundary(_fault, n_steps)
            g = _guardian.current() if guard is not None else None
            if g is not None:
                # one-window-lag sentinel: observe the PREVIOUS dispatch's
                # aggregated health and apply policy BEFORE this window runs
                g.on_boundary()
            t_stage0 = _time.perf_counter()
            state_vals = self._gather_state(program, plan, scope)
            mut_names = set(plan.state_out)
            if plan.needs_rng:
                mut_names.add(RNG_STATE_VAR)
            if guard is not None and guard.scale_vars:
                mut_names.update(guard.scale_vars)
            mut_state = {k: v for k, v in state_vals.items()
                         if k in mut_names}
            const_state = {k: v for k, v in state_vals.items()
                           if k not in mut_names}
            device = core.get_jax_device(self.place)
            feed_dev = {k: self._put_feed(k, v, device)
                        for k, v in feed_arrays.items()}
            t_stage1 = _time.perf_counter()
            sentinel = None
            dump_state = None
            if guard is not None:
                seed_mul, loss_mul = _fault.sentinel_injection_window(
                    window_start, n_steps)
                sentinel = {
                    "loss_cap": np.float32(g.loss_cap() if g is not None
                                           else float("inf")),
                    "seed_mul": seed_mul,
                    "loss_mul": loss_mul,
                }
                dump_state = state_vals
                if g is not None and g.config.policy == "dump_and_halt" \
                        and self._donate_argnums(device, program):
                    # donation invalidates mutated input buffers after the
                    # dispatch; dump mode keeps pre-window device copies
                    # alive
                    dump_state = {k: (jnp.array(v, copy=True)
                                      if k in mut_names else v)
                                  for k, v in state_vals.items()}
            if wspan is not None and entry_info.get("cost") is None:
                # device-time + memory attribution (tracing only): the
                # lowering costs one extra trace; reading memory_analysis
                # additionally needs a compile, so the traced first window
                # of an entry pays one extra backend compile (deduped by
                # the persistent backend cache when enabled) — the price
                # of the memory.peak_bytes truth gauge on this path
                try:
                    lowered = fn.lower(feed_dev, const_state, mut_state,
                                       sentinel)
                    entry_info["cost"] = _trace.cost_of(lowered) or False
                    from ..observe import memory as _obsmem

                    entry_info["memory"] = _obsmem.memory_stats(
                        lowered.compile()) or False
                    _obsmem.note_compiled_memory(
                        entry_info["memory"] or None, kind="run_steps",
                        n_steps=n_steps)
                except Exception:
                    entry_info.setdefault("cost", False)
                    entry_info["memory"] = False

            agg = None
            t = _time.perf_counter()
            if guard is not None:
                fetches, new_state, agg = fn(feed_dev, const_state,
                                             mut_state, sentinel)
            else:
                fetches, new_state = fn(feed_dev, const_state, mut_state,
                                        None)
            if wspan is not None or (_prof.is_profiling()
                                     and guard is None):
                # attribution needs the true device time; outside tracing/
                # profiling the dispatch stays async as before
                jax.block_until_ready((fetches, new_state))
            t_disp1 = _time.perf_counter()
            if _prof.is_profiling():
                _prof.record_event(
                    f"executor_run[{len(plan.ops)}ops x{n_steps}steps]",
                    t_disp1 - t, start=t)
            # window visibility in the always-on counters (the smoke oracle
            # counts dispatches; window_steps tracks amortization)
            _prof.record_counter("executor.dispatches")
            _prof.record_counter("executor.windows")
            _prof.record_counter("executor.window_steps", inc=n_steps)
            if probe is not None:
                meta = {"kind": "run_steps", "n_steps": n_steps}
                if isinstance(entry_info.get("memory"), dict):
                    # per-executable memory table in the cache manifest:
                    # a warm start re-reports it without re-lowering
                    meta["memory"] = entry_info["memory"]
                probe.finish(t_disp1 - t, program, meta=meta)
            if _fault.active() is not None:
                new_state = _fault.corrupt_state(new_state)
            for name, val in new_state.items():
                scope.set(name, val)
            self._check_nan_inf(list(new_state.items())
                                + list(zip(plan.fetch_names, fetches)))
            if g is not None and agg is not None:
                g.defer(guard, window_start, agg, {
                    "program": program, "feeds": feed_arrays,
                    "feed_lods": {}, "fetch_names": fetch_names,
                    "state": dump_state, "sentinel": sentinel,
                    "duration_s": t_disp1 - t,
                    "window": {"start": window_start, "n_steps": n_steps,
                               "feed_per_step": bool(feed_per_step)}})
            if program._params_grads is not None:
                from .. import observe
                from ..observe import memory as _obsmem

                # events emitted after the window (checkpoint commits, cache
                # probes) correlate to its LAST executed step, not its first
                observe.note_step(window_start + n_steps - 1)
                # live-buffer ledger: scope residency + watermark at the
                # window boundary (gauges, high-water, watchdog feed)
                _obsmem.note_scope_live(scope, scope_label="train",
                                        step=window_start + n_steps - 1)
            t_obs1 = _time.perf_counter()
            if wspan is not None:
                # child spans: H2D staging / device dispatch / host observe
                # tail — the step-time breakdown the trace view decomposes a
                # window into (host_ms = everything not in the other three)
                _trace.emit_span("executor.stage", t_stage0, t_stage1,
                                 parent=wspan)
                _trace.emit_span("executor.dispatch", t, t_disp1,
                                 parent=wspan, compile=fresh_entry)
                _trace.emit_span("executor.observe", t_disp1, t_obs1,
                                 parent=wspan)
                _trace.note_window_breakdown(
                    host_ms=((t_stage0 - t_host0) + (t - t_stage1)) * 1e3,
                    stage_ms=(t_stage1 - t_stage0) * 1e3,
                    device_ms=(t_disp1 - t) * 1e3,
                    observe_ms=(t_obs1 - t_disp1) * 1e3)
                if entry_info.get("cost"):
                    _trace.note_device_cost(entry_info["cost"],
                                            t_disp1 - t, n_steps,
                                            device=device)
            if program._params_grads is not None:
                # SLO watchdog: per-step time of this dispatch (no-op
                # unless PADDLE_SLO is armed)
                _watchdog.observe_value(
                    "executor.step_time_s",
                    (t_obs1 - t_host0) / max(1, n_steps),
                    step=window_start + n_steps - 1)
                from ..observe import goodput as _goodput

                # goodput ledger: a fresh entry's first dispatch is
                # compile cost (lazy jit), everything else device compute
                disp = t_disp1 - t
                if fresh_entry:
                    _goodput.note("compile", disp)
                    _goodput.note("device",
                                  max(0.0, (t_obs1 - t_host0) - disp))
                else:
                    _goodput.note("device", t_obs1 - t_host0)
            return [np.asarray(v) for v in fetches]

    def run(self, program=None, feed=None, fetch_list=None, feed_var_name="feed",
            fetch_var_name="fetch", scope=None, return_numpy=True,
            use_program_cache=True):
        program = program or default_main_program()
        feed = dict(feed or {})
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        # host infeed: pop one batch per `read` op from its reader queue
        # and make it this step's feed (ref: the C++ read op pulls from
        # LoDTensorBlockingQueue inside the executor loop)
        for op in program.global_block().ops:
            if op.type != "read":
                continue
            from .layers import io as _io
            from .lod_tensor import LoDTensor

            state = _io._reader_state(op.inputs["Reader"][0])
            batch = state.next_batch()  # raises core.EOFException
            for name, (arr, lod) in zip(op.outputs["Out"], batch):
                feed[name] = LoDTensor(arr, lod) if lod else arr

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        feed_arrays, feed_lods = {}, {}
        for k, v in feed.items():
            arr, lod = self._coerce_feed(program, k, v)
            feed_arrays[k] = arr
            if lod:
                feed_lods[k] = lod

        program = self._prune_for_unfed(program, feed_arrays, fetch_names,
                                        scope)

        # lods recorded on persistable state vars by earlier runs re-enter
        # the trace as static metadata, exactly like feed lods
        state_lods = {n: lod for n, lod in scope._lods.items()
                      if lod and program.global_block()._has_var_recursive(n)}

        from . import amp as _amp
        from . import guardian as _guardian

        # guarded training step: the numerics sentinel / dynamic loss
        # scaler fold a health reduction + conditional state commit into
        # the same jitted program (guardian.py module docstring)
        guard = _guardian.for_program(program)

        key = (program._cache_token, program._version, tuple(fetch_names),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())),
               tuple(sorted(feed_lods.items())),
               tuple(sorted(state_lods.items())),
               self.place.device_type,
               # execution-mode toggles invalidate compiled fns
               _amp.compute_dtype(),
               guard.cache_token() if guard is not None else None,
               os.environ.get("PADDLE_TPU_FLASH", ""),
               os.environ.get("PADDLE_TPU_FUSED", ""))
        entry = self._cache.get(key) if use_program_cache else None
        probe = None
        fresh_run_entry = entry is None
        if entry is None:
            from .log import VLOG
            from .. import analysis as _analysis
            from .. import compile_cache as _cc

            # pre-compile verifier (PADDLE_TPU_VERIFY=warn|strict|off):
            # named diagnostics in milliseconds instead of an XLA trace
            # error seconds into compile
            _analysis.check_before_compile(
                program, feed=feed_arrays, fetch_list=fetch_names,
                kind="run")
            # persistent-cache consult BEFORE tracing (hit/miss counters +
            # backend warm start through the shared jax disk cache)
            probe = _cc.executor_probe(
                program, feed_arrays, fetch_names,
                extra={"kind": "run",
                       "feed_lods": tuple(sorted(feed_lods.items())),
                       "state_lods": tuple(sorted(state_lods.items())),
                       "platform": self.place.device_type,
                       "amp": _amp.compute_dtype(),
                       "guard": (guard.cache_token()
                                 if guard is not None else None),
                       "flash": os.environ.get("PADDLE_TPU_FLASH", ""),
                           "fused": os.environ.get("PADDLE_TPU_FUSED", "")})
            VLOG(1, f"Executor: compiling block "
                    f"({len(program.global_block().ops)} ops, "
                    f"fetches={fetch_names})")
            plan_fetches = list(fetch_names)
            if guard is not None:
                plan_fetches += guard.extra_fetch_names()
            plan = BlockPlan(program, 0, list(feed_arrays), plan_fetches)
            if guard is not None and plan.needs_eager:
                if guard.scale_vars is not None:
                    raise RuntimeError(
                        "dynamic fp16 loss scaling is not supported for "
                        "programs with data-dependent eager ops")
                warnings.warn(
                    "guardian: program contains data-dependent eager ops; "
                    "the numerics sentinel is disabled for it")
                guard = None
                plan = BlockPlan(program, 0, list(feed_arrays), fetch_names)
            if guard is not None and guard.scale_vars:
                # the good-steps counter is read/written only by the
                # guarded wrapper (no IR op touches it), so liveness never
                # saw it — gather it with the rest of the state
                for n in guard.scale_vars:
                    if n not in plan.state_in:
                        plan.state_in.append(n)
            lod_box = {}
            all_lods = dict(state_lods)
            all_lods.update(feed_lods)
            fn = self._build(program, plan, all_lods, lod_box,
                             guard=guard, n_user=len(fetch_names))
            entry = (plan, fn, lod_box, guard)
            if use_program_cache:
                self._cache[key] = entry
        plan, fn, lod_box, guard = entry

        from . import fault as _fault

        step_idx = 0
        if program._params_grads is not None:
            # training-step boundary (programs built via optimizer.minimize;
            # hook points for fault injection + elastic liveness)
            step_idx = self._step_boundary(_fault)
        g = _guardian.current() if guard is not None else None
        if g is not None:
            # one-step-lag sentinel: observe the PREVIOUS step's health
            # (its dispatch has retired — materializing two scalars is
            # free) and apply policy BEFORE this step runs
            g.on_boundary()
        state_vals = self._gather_state(program, plan, scope)
        device = core.get_jax_device(self.place)
        feed_dev = {k: self._put_feed(k, v, device)
                    for k, v in feed_arrays.items()}

        # only vars that get rewritten are donated; read-only state (lr,
        # params in eval programs) must keep its buffers alive in the scope
        mut_names = set(plan.state_out)
        if plan.needs_rng:
            mut_names.add(RNG_STATE_VAR)
        mut_state = {k: v for k, v in state_vals.items() if k in mut_names}
        const_state = {k: v for k, v in state_vals.items()
                       if k not in mut_names}
        sentinel = None
        dump_state = None
        if guard is not None:
            seed_mul, loss_mul = _fault.sentinel_injection(step_idx)
            sentinel = {
                "loss_cap": np.float32(g.loss_cap() if g is not None
                                       else float("inf")),
                "seed_mul": np.float32(seed_mul),
                "loss_mul": np.float32(loss_mul),
            }
            dump_state = state_vals
            if g is not None and g.config.policy == "dump_and_halt" \
                    and self._donate_argnums(device, program):
                # donation invalidates mutated input buffers after the
                # dispatch; dump mode keeps pre-step device copies alive
                dump_state = {k: (jnp.array(v, copy=True) if k in mut_names
                                  else v)
                              for k, v in state_vals.items()}
        from . import profiler as _prof

        health = None
        import time as _time

        t = _time.perf_counter()
        if guard is not None:
            fetches, new_state, health = fn(feed_dev, const_state,
                                            mut_state, sentinel)
        elif _prof.is_profiling():
            fetches, new_state = fn(feed_dev, const_state, mut_state)
            jax.block_until_ready(fetches)
        else:
            fetches, new_state = fn(feed_dev, const_state, mut_state)
        if _prof.is_profiling():
            _prof.record_event(
                f"executor_run[{len(plan.ops)}ops]",
                _time.perf_counter() - t, start=t)
        _prof.record_counter("executor.dispatches")
        if probe is not None:
            # first dispatch of a fresh entry = trace + compile; commit the
            # artifact (miss) / freshen it (hit) now that it exists
            probe.finish(_time.perf_counter() - t, program,
                         meta={"kind": "run",
                               "ops": len(plan.ops),
                               "fetches": len(plan.fetch_names)})
        if _fault.active() is not None:
            new_state = _fault.corrupt_state(new_state)
        for name, val in new_state.items():
            scope.set(name, val)
            if name in lod_box:
                scope._lods[name] = lod_box[name]
        self._check_nan_inf(list(new_state.items())
                            + list(zip(plan.fetch_names, fetches)))
        if g is not None and health is not None:
            g.defer(guard, step_idx, health, {
                "program": program, "feeds": feed_arrays,
                "feed_lods": feed_lods, "fetch_names": fetch_names,
                "state": dump_state, "sentinel": sentinel,
                "duration_s": _time.perf_counter() - t})
        if program._params_grads is not None:
            from ..observe import memory as _obsmem
            from ..observe import watchdog as _watchdog

            # SLO watchdog on the per-step training path (no-op unless
            # PADDLE_SLO is armed); async dispatch means this measures
            # submit-to-submit pacing, which is what regresses under load
            _watchdog.observe_value("executor.step_time_s",
                                    _time.perf_counter() - t, step=step_idx)
            # ledger gauges only (quiet): per-step watermark EVENTS would
            # flood the stream — windows own the event cadence
            _obsmem.note_scope_live(scope, scope_label="train",
                                    step=step_idx, emit_event=False)
            from ..observe import goodput as _goodput

            # per-step training dispatch: a fresh entry's first dispatch
            # is compile cost (lazy jit), everything after device compute
            _goodput.note("compile" if fresh_run_entry else "device",
                          _time.perf_counter() - t)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        from .lod_tensor import LoDTensor

        # keep fetches device-resident: conversion happens lazily on first
        # numpy access, so a training loop that only inspects the loss
        # occasionally is not throttled by one D2H sync per step.  A fetch
        # that is ALSO a mutated state var aliases a buffer the next run
        # will donate — copy those on device so the returned handle survives
        # (donation would otherwise delete it under the caller).
        donated = set(plan.state_out) | ({RNG_STATE_VAR} if plan.needs_rng
                                         else set())
        out = []
        for n, v in zip(plan.fetch_names, fetches):
            if n in donated and isinstance(v, jax.Array):
                v = jnp.array(v, copy=True)
            out.append(LoDTensor(v, lod_box.get(n)))
        return out

    def compiled_memory_stats(self, program, feed, fetch_list, scope=None):
        """Compiled-truth memory stats for one (program, feed)
        specialization: AOT lower + compile the SAME traced step
        ``Executor.run`` would jit and read the backend's
        ``memory_analysis()``.  Costs one backend compile (deduped by the
        persistent backend cache when enabled) — callers own that
        decision: ``ServingEngine.warmup`` (the precompile path by
        definition) and the memcheck cross-check tests.  Returns the
        ``observe.memory.memory_stats`` dict, or None (eager-island
        programs, backends without memory analysis)."""
        scope = scope or global_scope()
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list or []]
        feed_arrays = {}
        for k, v in dict(feed or {}).items():
            arr, lod = self._coerce_feed(program, k, v)
            if lod:
                return None  # LoD programs re-trace per lod; no one truth
            feed_arrays[k] = arr
        program = self._prune_for_unfed(program, feed_arrays, fetch_names,
                                        scope)
        plan = BlockPlan(program, 0, list(feed_arrays), fetch_names)
        if plan.needs_eager:
            return None
        try:
            fn = self._build(program, plan)
            device = core.get_jax_device(self.place)

            def norm(v):
                # a scope that last committed a SHARDED run holds mesh
                # arrays; gather them so the probe lowers single-device
                if isinstance(v, jax.Array) and len(v.devices()) > 1:
                    v = np.asarray(v)
                return jax.device_put(jnp.asarray(v), device)

            state_vals = {k: norm(v) for k, v in
                          self._gather_state(program, plan, scope).items()}
            mut_names = set(plan.state_out)
            if plan.needs_rng:
                mut_names.add(RNG_STATE_VAR)
            mut_state = {k: v for k, v in state_vals.items()
                         if k in mut_names}
            const_state = {k: v for k, v in state_vals.items()
                           if k not in mut_names}
            feed_dev = {k: jax.device_put(jnp.asarray(v), device)
                        for k, v in feed_arrays.items()}
            compiled = fn.lower(feed_dev, const_state, mut_state).compile()
            from ..observe import memory as _obsmem

            return _obsmem.memory_stats(compiled)
        except Exception:
            return None

    # -- helpers --
    @staticmethod
    def _donate_argnums(device, program):
        """Donation argnums for the jitted step: the mutable-state arg
        (index 2) is donated so XLA aliases its buffers into the updated
        state — a true in-place parameter update.  Modern jax implements
        donation on every backend (cpu/gpu/tpu), and the executor already
        protects the one read-after-donate hazard (fetches aliasing
        mutated state are copied on return, executor.run's donated-fetch
        path), so it is on for every TRAINING program (built via
        optimizer.minimize, whose step loop is single-threaded by
        contract).  Inference/eval programs never donate: predictor
        clones run concurrently against one shared scope, and a donated
        buffer deleted under a sibling thread's in-flight dispatch is the
        one hazard copy-on-return cannot fix.  ``PADDLE_TPU_DONATE=0``
        opts out entirely (debugging buffer lifetimes).

        Exception to the inference rule: a program that sets
        ``_donate_state = True`` (the serving DecodeEngine's decode-step
        / prefill programs, whose persistable KV cache is rewritten by
        exactly one engine worker thread per the single-dispatcher
        contract) opts back in, so the [max_slots, max_len, ...] cache
        buffers alias window-over-window instead of copying every
        tick."""
        if program is not None and program._params_grads is None \
                and not getattr(program, "_donate_state", False):
            return ()
        from . import envcontract

        if not envcontract.get("PADDLE_TPU_DONATE"):
            return ()
        return (2,)

    @staticmethod
    def _step_boundary(_fault, n_steps=1):
        """Training-step boundary: fires armed step faults (kill-at-step-N)
        and emits an elastic-supervisor heartbeat when a heartbeat dir is
        configured.  A fused run_steps dispatch advances the whole window at
        once — a kill armed inside it fires before the dispatch.  Returns
        the step index this dispatch executes (window start for fused)."""
        fired = _fault.current_step()
        if _fault.active() is not None:
            if n_steps == 1:
                fired = _fault.on_step()
            else:
                _fault.advance(n_steps)
            # straggler oracle: the armed rank's sleep lands here, INSIDE
            # the window span, so its per-step time inflates like a real
            # slow chip's and the skew detector must flag it
            _fault.straggler_delay(n_steps)
        else:
            _fault._step += n_steps  # keep the index flowing for the guardian
        from .. import observe

        # every subsystem's events from here to the next boundary correlate
        # to this step (guardian trips, cache hits, checkpoint commits)
        observe.note_step(fired)
        hb_dir = os.environ.get("PADDLE_ELASTIC_HB_DIR")
        if hb_dir:
            from ..parallel.elastic import write_heartbeat

            write_heartbeat(hb_dir, step=_fault.current_step())
        return fired

    @staticmethod
    def _check_nan_inf(named_vals):
        """Debug mode (ref FLAGS_check_nan_inf, operator.cc:643): fault
        with the variable NAME on the first non-finite value.  Host-side
        materialization forces a sync per step — debug only."""
        if not core.GLOBAL_FLAGS.get("check_nan_inf"):
            return
        for name, val in named_vals:
            arr = np.asarray(val)
            if np.issubdtype(arr.dtype, np.floating) \
                    and not np.isfinite(arr).all():
                raise FloatingPointError(
                    f"check_nan_inf: variable '{name}' contains "
                    f"NaN/Inf after op block execution")

    def _put_feed(self, name, arr, device):
        """H2D-transfer a feed value, skipping the copy when the bytes are
        identical to what this feed name already holds on device.

        Safety: a full host-side ``array_equal`` guards the hit (memcmp at
        host memory bandwidth — orders of magnitude cheaper than re-shipping
        over PCIe or a tunneled transport), so in-place mutation of a reused
        feed buffer is still detected and re-transferred.  Values that are
        already jax Arrays (e.g. pre-placed by the caller) pass through.
        """
        if isinstance(arr, jax.Array):
            if device in arr.devices():
                return arr
            return jax.device_put(arr, device)
        if device.platform == "cpu":
            # host device: device_put is (near) free; skip cache bookkeeping
            return jax.device_put(arr, device)
        ent = self._feed_cache.get(name)
        if ent is not None:
            snap, dev_arr, misses = ent
            if misses is None:
                # retired entry: snap records the (shape, dtype) that
                # retired it.  Same geometry keeps transferring (fresh
                # batches every step), but a geometry CHANGE — e.g. the
                # name switching from train batches to a fixed eval feed —
                # re-arms the cache instead of transferring forever
                if snap == (arr.shape, str(arr.dtype)):
                    return jax.device_put(arr, device)
                ent = None
            elif snap.shape == arr.shape and snap.dtype == arr.dtype \
                    and np.array_equal(snap, arr):
                ent[2] = 0
                return dev_arr
            elif misses + 1 >= 3:
                # fresh batch every step (the normal training loop): stop
                # paying the compare+snapshot tax and just transfer
                self._feed_cache[name] = [(arr.shape, str(arr.dtype)),
                                          None, None]
                return jax.device_put(arr, device)
        dev_arr = jax.device_put(arr, device)
        prev_misses = ent[2] if ent is not None else 0
        self._feed_cache[name] = [np.array(arr, copy=True), dev_arr,
                                  prev_misses + 1 if ent is not None else 0]
        return dev_arr

    def _build(self, program, plan, feed_lods=None, lod_box=None,
               guard=None, n_user=None):
        device = core.get_jax_device(self.place)
        donate = self._donate_argnums(device, program)
        static_env = {k + LOD_SUFFIX: lod
                      for k, lod in (feed_lods or {}).items()}

        if guard is not None:
            from . import guardian as _g

            def gfn(feed_vals, const_state, mut_state, sentinel):
                state = dict(const_state)
                state.update(mut_state)
                feed_vals = dict(feed_vals)
                # backward-seed multiplier (loss scale x fault injection),
                # consumed by the __loss_seed__-tagged op in run_op
                feed_vals[_g.LOSS_SEED_MUL] = _g.seed_multiplier(
                    guard, state, sentinel)
                fetches, new_state = trace_block(
                    program, 0, plan, feed_vals, state,
                    static_env=static_env, lod_box=lod_box)
                new_state, health = _g.fold_health(
                    guard, fetches[n_user:], new_state, mut_state, state,
                    sentinel)
                return fetches[:n_user], new_state, health

            return jax.jit(gfn, donate_argnums=donate)

        def fn(feed_vals, const_state, mut_state):
            state = dict(const_state)
            state.update(mut_state)
            return trace_block(program, 0, plan, feed_vals, state,
                               static_env=static_env, lod_box=lod_box)

        if plan.needs_eager:
            # programs with data-dependent ops (beam search, mask split):
            # eager-ISLAND execution — contiguous traceable runs compile as
            # cached jit segments, only the islands run op-by-op
            # (SURVEY.md §7 hard part #1/#2)
            return self._build_segmented(plan, static_env, lod_box)
        return jax.jit(fn, donate_argnums=donate)

    def _build_segmented(self, plan, static_env, lod_box):
        seg_cache: Dict[tuple, tuple] = {}

        def _classify(v):
            return "arr" if isinstance(v, jax.Array) else "host"

        def run_segments(feed_vals, const_state, mut_state):
            env: Dict[str, object] = {}
            env.update(static_env)
            env.update(const_state)
            env.update(mut_state)
            env.update(feed_vals)
            rng_box = [env[RNG_STATE_VAR]] if plan.needs_rng else None
            from . import profiler as _prof

            for si, (kind, ops) in enumerate(plan.segments):
                if kind == "eager":
                    for op in ops:
                        if _prof.is_profiling():
                            import time as _time

                            t = _time.perf_counter()
                            run_op(op, env, rng_box)
                            _prof.record_event(
                                f"eager:{op.type}",
                                _time.perf_counter() - t, start=t)
                        else:
                            run_op(op, env, rng_box)
                    continue
                if _prof.is_profiling():
                    import time as _time

                    t = _time.perf_counter()
                    self._run_jit_segment(si, ops, env, rng_box, seg_cache)
                    _prof.record_event(
                        f"jit_segment[{si}:{len(ops)}ops]",
                        _time.perf_counter() - t, start=t)
                else:
                    self._run_jit_segment(si, ops, env, rng_box, seg_cache)
            fetches = [env[n] for n in plan.fetch_names]
            new_state = {n: env[n] for n in plan.state_out if n in env}
            if rng_box is not None:
                new_state[RNG_STATE_VAR] = rng_box[0]
            if lod_box is not None:
                for n in list(plan.fetch_names) + list(plan.state_out):
                    lod = env.get(n + LOD_SUFFIX)
                    if lod is not None:
                        lod_box[n] = lod
            return fetches, new_state

        return run_segments

    def _run_jit_segment(self, si, ops, env, rng_box, seg_cache):
        """Run one traceable segment through a cached jitted function.

        Device (jax) values in the env become traced arguments; host values
        (numpy counters, LoD tuples, forward-host stashes) are trace-time
        constants keyed into the cache, so a host change retraces while the
        steady state (e.g. the encoder prefix of a decode program) reuses
        one compiled executable.  Host values PRODUCED at trace time are
        replayed from the cache — they are deterministic functions of the
        host inputs."""
        import hashlib

        from ..ops.array_ops import TensorArray

        def _is_traceable(v):
            if isinstance(v, jax.Array):
                return True
            if isinstance(v, TensorArray):
                return any(isinstance(x, (jax.Array, jax.core.Tracer))
                           for x in v.vals if x is not None)
            return False

        arr_in: Dict[str, object] = {}
        host_env: Dict[str, object] = {}
        for name, val in env.items():
            if _is_traceable(val):
                arr_in[name] = val
            else:
                host_env[name] = val

        from ..ops.array_ops import RankTable

        def _host_key(v):
            if isinstance(v, np.ndarray):
                return (v.shape, str(v.dtype),
                        hashlib.blake2b(v.tobytes(), digest_size=8).hexdigest())
            if isinstance(v, dict):
                return tuple(sorted((str(k), _host_key(x))
                                    for k, x in v.items()))
            if isinstance(v, (list, tuple)):
                return tuple(_host_key(x) for x in v)
            if isinstance(v, RankTable):
                return ("ranktable", tuple(map(tuple, v.items)))
            if isinstance(v, TensorArray):  # host-valued array
                return ("ta", tuple(_host_key(x) for x in v.vals),
                        _host_key(v.lods))
            if v is None or isinstance(v, (bool, int, float, str, bytes)):
                return v
            # unknown host object: key by content so equal values hit the
            # cache and changed values retrace (identity keying would either
            # never hit or replay stale trace-time constants)
            import pickle

            try:
                return ("pickled", hashlib.blake2b(
                    pickle.dumps(v), digest_size=8).hexdigest())
            except Exception:
                return ("id", id(v))

        def _arr_sig(v):
            if isinstance(v, jax.Array):
                return (tuple(v.shape), str(v.dtype))
            # TensorArray: per-element shape signature
            return tuple((tuple(x.shape), str(x.dtype)) if x is not None
                         else None for x in v.vals)

        # '@'-prefixed entries (forward-host stashes) ARE part of the key:
        # they get baked into the trace as constants, so a changed stash
        # must miss the cache, not silently replay into grad ops
        key = (si,
               tuple(sorted((n, _arr_sig(v)) for n, v in arr_in.items())),
               _host_key(host_env))
        entry = seg_cache.get(key)
        if entry is None:
            side = {}
            captured_host = dict(host_env)

            def traced(arrs, rng_key):
                env2: Dict[str, object] = dict(captured_host)
                env2.update(arrs)
                before = {n: id(v) for n, v in env2.items()}
                box = [rng_key] if rng_key is not None else None
                for op in ops:
                    run_op(op, env2, box)
                from ..ops.array_ops import TensorArray as _TA

                arr_out, host_out = {}, {}
                for n, v in env2.items():
                    if before.get(n) == id(v):
                        continue
                    if isinstance(v, (jax.Array, jax.core.Tracer, _TA)):
                        arr_out[n] = v
                    else:
                        host_out[n] = v
                side["host"] = host_out
                return arr_out, (box[0] if box is not None else None)

            jitted = jax.jit(traced)
            entry = (jitted, side)
            seg_cache[key] = entry
        jitted, side = entry
        arr_out, new_key = jitted(arr_in, rng_box[0] if rng_box else None)
        env.update(arr_out)
        env.update(side.get("host", {}))
        if rng_box is not None and new_key is not None:
            rng_box[0] = new_key

    def _prune_for_unfed(self, program, feed_arrays, fetch_names, scope):
        """Reference executors run whole mixed programs and tolerate
        unfed data vars in NON-fetched branches (book decode_main reuses
        the train program's default main; the C++ ops just see empty
        tensors).  The static-shape equivalent: when an unfed data var
        exists, prune to the fetch targets — dropping backward/optimize
        ops like the reference's pruning (prune.cc honors op roles) so a
        kept decode branch does not drag the train branch back in via
        shared parameters.  If the unfed var is still needed after
        pruning, keep the original program so the clear 'was not fed'
        error fires."""
        if not fetch_names:
            return program
        gb = program.global_block()
        # cheap first: the (small) set of declared-but-unfed data vars
        candidates = [v.name for v in gb.vars.values()
                      if getattr(v, "is_data", False)
                      and v.name not in feed_arrays
                      and scope.get(v.name, None) is None]
        if not candidates:
            return program
        consumed = set()
        for op in gb.ops:
            consumed.update(op.input_arg_names)
        unfed = sorted(n for n in candidates if n in consumed)
        if not unfed:
            return program
        # cache holds ONE version's entries; a program mutation replaces
        # it wholesale (each entry pins a full clone)
        cache_ver, cache = getattr(program, "_unfed_prune_cache",
                                   (None, None))
        if cache_ver != program._version:
            cache = {}
            program._unfed_prune_cache = (program._version, cache)
        key = (tuple(fetch_names), tuple(unfed))
        pruned = cache.get(key)
        if pruned is None:
            pruned = self._try_prunes(program, fetch_names, unfed, scope,
                                      feed_arrays)
            cache[key] = pruned
        return pruned

    @staticmethod
    def _try_prunes(program, fetch_names, unfed, scope, feed_arrays):
        """Two attempts, most-conservative first:

        A. liveness slice keeping persistable-writers (BlockPlan's rule)
           — a TRAIN fetch keeps its optimizer while an unrelated unfed
           decode branch drops away;
        B. role-dropping slice (no backward/optimize, the reference's
           inference pruning) — a DECODE fetch sheds the whole train
           branch that shares its parameters.

        Adopt an attempt only if it clears every unfed var AND still
        produces all fetches; else the original program keeps the clear
        'was not fed' error."""

        def _viable(p):
            produced, consumed = set(), set()
            for op in p.global_block().ops:
                produced.update(op.output_arg_names)
                consumed.update(op.input_arg_names)
            if any(n in consumed for n in unfed):
                return False
            for f in fetch_names:
                if f not in produced and f not in feed_arrays \
                        and scope.get(f, None) is None:
                    return False
            return True

        # attempt A: keep persistable-writers
        a = program.clone()
        gb = a.global_block()

        def _writes_persistable(op):
            return any(gb._has_var_recursive(n)
                       and gb._var_recursive(n).persistable
                       for n in op.output_arg_names)

        needed = set(fetch_names)
        kept = []
        for op in reversed(gb.ops):
            if any(n in needed for n in op.output_arg_names) \
                    or _writes_persistable(op):
                kept.append(op)
                needed.update(op.input_arg_names)
        gb.ops = list(reversed(kept))
        if _viable(a):
            return a

        # attempt B: drop backward/optimize like inference pruning
        b = program._prune(fetch_names,
                           drop_roles=(OpRole.Backward, OpRole.Optimize))
        if _viable(b):
            return b
        return program  # pruning cannot help; keep the error

    def _gather_state(self, program, plan, scope):
        state = {}
        for name in plan.state_in:
            val = scope.get(name, _MISSING)
            if val is _MISSING:
                gb = program.global_block()
                if gb._has_var_recursive(name) and \
                        gb._var_recursive(name).is_data:
                    raise RuntimeError(
                        f"Data variable '{name}' was not fed. Pass it in the "
                        f"feed dict (feed keys were misspelled or missing).")
                raise RuntimeError(
                    f"Variable '{name}' is not initialized in the scope. "
                    f"Did you run the startup program?")
            state[name] = val if isinstance(val, jax.Array) else jnp.asarray(val)
        if plan.needs_rng:
            rk = scope.get(RNG_STATE_VAR, _MISSING)
            if rk is _MISSING:
                rk = jax.random.PRNGKey(program.random_seed or 0)
                scope.set(RNG_STATE_VAR, rk)
            state[RNG_STATE_VAR] = rk
        return state

    def _coerce_feed(self, program, name, value):
        lod = None
        from .lod_tensor import LoDTensor

        if isinstance(value, LoDTensor):
            lod = value.lod() or None
            # unwrap WITHOUT np.asarray: a device-resident LoDTensor (what
            # run(return_numpy=False) returns) must stay on device — the
            # jax.Array branch below passes it through, avoiding a blocking
            # D2H + re-upload round trip on the decode hot path
            value = value._data
        elif isinstance(value, tuple) and len(value) == 2 \
                and isinstance(value[1], (list, tuple)):
            # (array, recursive_sequence_lengths) convenience form
            from .lod_tensor import _lengths_to_offsets

            value, lengths = value
            lod = tuple(tuple(_lengths_to_offsets(l)) for l in lengths) or None
        if isinstance(value, jax.Array):
            # pre-placed device array: keep it on device (astype stays lazy)
            gb = program.global_block()
            if gb._has_var_recursive(name):
                want = core.np_dtype(gb._var_recursive(name).dtype)
                if value.dtype != want:
                    value = value.astype(want)
            return value, lod
        arr = np.asarray(value)
        gb = program.global_block()
        if gb._has_var_recursive(name):
            want = core.np_dtype(gb._var_recursive(name).dtype)
            if arr.dtype != want:
                arr = arr.astype(want)
        if lod is not None:
            lod = tuple(tuple(int(x) for x in level) for level in lod)
        return arr, lod
