"""Checkpoint / model save-load (ref: python/paddle/fluid/io.py:89-677).

Serialization format: one file per variable inside ``dirname`` (same layout
contract as the reference's save/load ops) with numpy's .npy encoding inside;
``save_inference_model`` writes a pickled pruned Program as ``__model__``.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from .executor import Executor, global_scope
from .framework import Parameter, Program, Variable, default_main_program

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "get_inference_program",
]


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def _resolve_vars(main_program, predicate, vars):
    main_program = main_program or default_main_program()
    if vars is not None:
        return [main_program.global_block()._var_recursive(v)
                if isinstance(v, str) else v for v in vars]
    return [v for v in main_program.list_vars() if predicate(v)]


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    predicate = predicate or is_persistable
    var_list = _resolve_vars(main_program, predicate, vars)
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        blob = {}
        for v in var_list:
            val = scope.get(v.name)
            if val is None:
                continue
            blob[v.name] = np.asarray(val)
        with open(os.path.join(dirname, filename), "wb") as f:
            np.savez(f, **blob)
        return
    write_var_files(dirname, snapshot_vars(scope, var_list))


def snapshot_vars(scope, var_list) -> dict:
    """Host-side {name: ndarray} snapshot of the vars present in scope
    (one D2H sync; shared by the sync and async checkpoint writers)."""
    snap = {}
    for v in var_list:
        val = scope.get(v.name)
        if val is not None:
            snap[v.name] = np.asarray(val)
    return snap


def write_var_files(dirname, snapshot: dict) -> None:
    """One file per var, np.save format — the single place that encodes
    the per-var on-disk layout (load_vars is its reader).  Each write is
    wrapped in bounded transient retry (``fluid.retry``): an OSError is
    a storage blip worth another attempt, never a reason to lose the
    serial."""
    from . import fault as _fault
    from .retry import retry_io

    for name, arr in snapshot.items():
        path = os.path.join(dirname, name)

        def _write(path=path, arr=arr):
            _fault.io_delay()
            _fault.io_error(path, "write")
            with open(path, "wb") as f:
                np.save(f, arr, allow_pickle=False)

        retry_io(_write, what="ckpt.var_write")


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_persistable, filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None, scope=None):
    predicate = predicate or is_persistable
    var_list = _resolve_vars(main_program, predicate, vars)
    scope = scope or global_scope()
    if filename is not None:
        with np.load(os.path.join(dirname, filename)) as data:
            for v in var_list:
                if v.name in data:
                    scope.set(v.name, data[v.name])
        return
    from . import fault as _fault
    from .retry import retry_io

    for v in var_list:
        path = os.path.join(dirname, v.name)
        if not os.path.exists(path):
            # matching the reference's load op, which faults on an absent
            # file (load_op.cc "cannot open file"): silently skipping leaves
            # random init in place — e.g. a program whose unique names
            # drifted from the saved model would "load" nothing and predict
            # noise with no error anywhere
            raise IOError(
                f"load_vars: no saved file for variable '{v.name}' in "
                f"{dirname} (program/name mismatch with the checkpoint?)")

        def _read(path=path):
            # transient OSError retries; a corrupt payload raises
            # ValueError from np.load and flows UNRETRIED to the
            # caller's serial-condemnation fallback (load_checkpoint)
            _fault.io_error(path, "read")
            with open(path, "rb") as f:
                return np.load(f, allow_pickle=False)

        scope.set(v.name, retry_io(_read, what="ckpt.var_read"))


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    load_vars(executor, dirname, main_program, None, is_parameter, filename,
              scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    load_vars(executor, dirname, main_program, None, is_persistable, filename,
              scope=scope)


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program._prune(target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    main_program = main_program or default_main_program()
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    os.makedirs(dirname, exist_ok=True)
    inference_program = main_program.clone(for_test=True)
    inference_program = inference_program._prune(target_vars)
    payload = {
        # versioned program blob (Program.serialize_to_string) so a future
        # format bump is detectable at load time
        "program_blob": inference_program.serialize_to_string(),
        "feed_names": list(feeded_var_names),
        "fetch_names": [t.name for t in target_vars],
    }
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "wb") as f:
        pickle.dump(payload, f)
    # persistables, not just Parameters: batch-norm moving stats and other
    # persistable state the pruned program reads must round-trip
    # (ref: io.py:561 save_inference_model → save_persistables)
    save_persistables(executor, dirname, inference_program, params_filename)
    return [t.name for t in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "rb") as f:
        payload = pickle.load(f)
    if "program_blob" in payload:
        program = Program.parse_from_string(payload["program_blob"])
    else:  # pre-versioned __model__ files
        program = payload["program"]
    load_persistables(executor, dirname, program, params_filename,
                      scope=scope)
    fetch_vars = [program.global_block()._var_recursive(n)
                  for n in payload["fetch_names"]]
    return program, payload["feed_names"], fetch_vars
