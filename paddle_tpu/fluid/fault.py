"""Deterministic fault injection for robustness testing.

The reference stack's fault tolerance was *testable* because its Go master
and pserver shipped with chaos hooks (go/master timeout requeue, pserver
checkpoint-on-notify); this module is the TPU build's equivalent: a single
place that can deterministically reproduce the failures a production pod
actually sees — preempted workers, checkpoints killed mid-write, slow/wedged
storage, silent NaNs, stalled collectives — so the recovery paths in
``trainer``/``multihost``/``parallel.elastic`` are exercised by fast tests
instead of discovered during multi-hour TPU wedges (VERDICT r5).

Faults are armed either programmatically (``install(FaultPlan(...))``) or
via environment flags, which is how the elastic supervisor injects them into
worker processes:

    PADDLE_FAULT_KILL_STEP=N      die at the step-N boundary (os._exit 137,
                                  a SIGKILL stand-in: no atexit, no flush)
    PADDLE_FAULT_RANK=r           restrict any armed fault to rank r
                                  (default: every rank; rank source is
                                  PADDLE_TRAINER_ID)
    PADDLE_FAULT_CKPT_CRASH=before|after
                                  crash during a checkpoint save, just
                                  before / just after the _SUCCESS marker
    PADDLE_FAULT_CKPT_POISON_SERIAL=n
                                  NaN-poison every float weight file of
                                  checkpoint serial n at save time —
                                  committed WITH a valid _SUCCESS marker,
                                  unlike the pre-commit corruption hooks:
                                  the checkpoint looks perfectly healthy
                                  to the watcher/loader and only the
                                  serving canary's output-sanity sentinel
                                  can catch it (the deterministic
                                  forced-bad-checkpoint oracle for the
                                  hot-swap auto-rollback path)
    PADDLE_FAULT_IO_DELAY_MS=t    sleep t ms inside every checkpoint write
    PADDLE_FAULT_NAN_VAR=name     overwrite var `name` with NaN once
    PADDLE_FAULT_NAN_STEP=N       ...at step N (default 0)
    PADDLE_FAULT_GRAD_INF_STEP=N  poison step N's backward seed so every
                                  gradient goes Inf IN-GRAPH (the guardian
                                  sentinel and fp16 loss scaler's overflow
                                  oracle; flows through the real grad ops,
                                  so a replay bundle reproduces it)
    PADDLE_FAULT_GRAD_INF_VALUE=v seed multiplier (default inf; a large
                                  finite value like 1e30 models a partial
                                  fp16 overflow instead)
    PADDLE_FAULT_LOSS_SPIKE_STEP=N
                                  multiply the observed loss at step N by
                                  PADDLE_FAULT_LOSS_SPIKE_FACTOR (default
                                  1e4) — the corrupt-batch oracle for the
                                  guardian's spike detector
    PADDLE_FAULT_BARRIER_STALL=s  sleep s seconds before the next collective
                                  barrier (one-shot), simulating a wedged
                                  host that trips the supervisor's timeout
    PADDLE_FAULT_HOST_LOSS_RANK=r
                                  permanent host loss: rank r exits hard at
                                  the PADDLE_FAULT_HOST_LOSS_AT_STEP step
                                  boundary AND drops a host_lost marker in
                                  the supervisor's heartbeat dir, so the
                                  survivor census sees a smaller fleet —
                                  unlike kill-at-step, the replacement
                                  generation cannot be the same size; the
                                  deterministic oracle for the supervisor's
                                  mesh-ladder downgrade (PADDLE_TPU_MESH_
                                  LADDER).  Keyed on its own rank knob like
                                  the straggler, so it composes with other
                                  rank-scoped faults in one scenario.
    PADDLE_FAULT_REPLICA_KILL_AFTER=n
                                  serving-fleet replica death: the fleet
                                  consults :func:`replica_kill` after every
                                  completed request; the call whose running
                                  total reaches n returns True ONCE, and
                                  the fleet kills the replica that served
                                  that request (resident futures fail, the
                                  pool census re-spawns it on surviving
                                  devices) — the deterministic oracle for
                                  the router's zero-shed failover and
                                  cache-hit re-warm path.  Never a process
                                  exit: a replica dies, the fleet survives.
    PADDLE_FAULT_SERVE_DELAY_MS=t sleep t ms per serving-engine request
                                  (slow-model / GC-pause simulation on the
                                  inference path)
    PADDLE_FAULT_SERVE_FAIL_EVERY=N
                                  fail every Nth serving request with an
                                  InjectedFault delivered on that request's
                                  future (the engine must isolate it: the
                                  rest of the batch still completes)
    PADDLE_FAULT_DECODE_STALL_MS=t
                                  stall every continuous-batching decode
                                  TICK t ms (DecodeEngine worker loop) —
                                  inflates inter-token latency on every
                                  in-flight stream at once, the
                                  deterministic oracle for the SLO
                                  watchdog's serving.intertoken_s breach
    PADDLE_FAULT_CACHE_CORRUPT=1  treat every persistent compile-cache
                                  entry load as corrupt (the deterministic
                                  oracle for the cache's fallback path:
                                  the run must recompile fresh and still
                                  succeed — see paddle_tpu.compile_cache)
    PADDLE_FAULT_DATA_STALL_MS=t  stall the input pipeline t ms per pulled
                                  sample (slow reader); with
                                  PADDLE_FAULT_DATA_STALL_AT=N the stall
                                  fires ONCE, at source-cursor N — the
                                  SLO-breach oracle for train.data_wait_s
    PADDLE_FAULT_SHARD_CORRUPT=1  truncate the next data_state blob write
                                  (one-shot): the resumed run must detect
                                  the corrupt cursor and fall back to the
                                  previous complete serial
    PADDLE_FAULT_STRAGGLER_RANK=r
                                  deterministic straggler oracle: rank r
                                  sleeps PADDLE_FAULT_STRAGGLER_MS ms per
                                  training step at the step boundary —
                                  INSIDE the executor window span, so the
                                  rank's per-step time inflates exactly
                                  like a thermally-throttled / failing
                                  chip's would and the cross-rank skew
                                  detector (observe.fleet.rank_skew) must
                                  flag it.  Keyed on its own rank knob, NOT
                                  PADDLE_FAULT_RANK: one scenario may kill
                                  rank 0 while rank 1 straggles.
    PADDLE_FAULT_MEM_PRESSURE=mb  synthesize a memory leak: starting at the
                                  PADDLE_FAULT_MEM_PRESSURE_AT-th (default
                                  8th) live-buffer-ledger observation, add
                                  mb MB of phantom live bytes, DOUBLING per
                                  observation — the deterministic oracle
                                  for the memory.live_bytes SLO breach and
                                  the PADDLE_MEM_BUDGET_MB over-budget
                                  event (see observe.memory)
    PADDLE_FAULT_KV_PAGE_LEAK=n   paged-KV leak oracle: the serving page
                                  pool's allocator SKIPS its next n page
                                  frees (one-shot), so retired requests
                                  leave pages marked live forever —
                                  kvpool.pages_free never returns to its
                                  initial level after drain, the
                                  kvpool.hbm_bytes gauge and live-buffer
                                  ledger climb, and the leak is
                                  deterministic enough for the memcheck /
                                  watchdog tests to assert on exact page
                                  counts (see serving.kvpool.PagePool)
    PADDLE_FAULT_SPEC_DRAFT_POISON=n  speculative-draft poison oracle:
                                  from engine tick n on, every token the
                                  draft model proposes is replaced with
                                  deterministic garbage, so draft
                                  acceptance collapses to ~1/vocab — the
                                  specdec adaptive controller must fire
                                  its specdec.fallback event while the
                                  emitted stream stays bitwise correct
                                  (every accepted/correction token is a
                                  target argmax regardless of what the
                                  draft proposed; see serving/specdec)
    PADDLE_FAULT_IO_ERROR_RATE=f  transient-storage oracle: the fraction
                                  f of (path, op) keys whose FIRST
                                  read/write attempt raises OSError —
                                  seeded (PADDLE_FAULT_IO_ERROR_SEED) and
                                  keyed on the path's tail, so the SAME
                                  files fail on every run and the retry
                                  attempt for a failed key always
                                  succeeds.  Transient by construction:
                                  bounded retry (fluid.retry.retry_io)
                                  must recover, while an unretried call
                                  site still sees a hard failure — and
                                  content corruption (ValueError) never
                                  goes through this hook, so the
                                  serial-condemnation fallback stays
                                  distinct from the transient path
    PADDLE_FAULT_MODE=exit|raise  crash flavor: hard process exit (default)
                                  or an InjectedFault raise (in-process
                                  tests of the recovery path)

Hook points (each a no-op costing one attribute read when nothing is
armed): ``Executor.run``/``run_steps`` call :func:`on_step` at the training
step boundary and :func:`corrupt_state` on the step's outputs;
``trainer.save_checkpoint``/``multihost.save_sharded_serial`` call
:func:`ckpt_crash_point` around their _SUCCESS writes and :func:`io_delay`
in their write loops; ``multihost.barrier`` calls :func:`barrier_stall`;
``serving.ServingEngine`` calls :func:`serving_request` once per admitted
request at batch formation.

Determinism contract: a fault keyed to step N fires exactly at step N of
the *caller-provided* step index when one is given (the elastic worker
passes its global resume-aware step, so a restarted worker never re-fires a
kill it already survived), else of an internal per-process counter.
"""

from __future__ import annotations

import os
import time
from typing import Optional

__all__ = [
    "FaultPlan", "InjectedFault", "install", "clear", "active",
    "on_step", "corrupt_state", "ckpt_crash_point", "ckpt_poison",
    "io_delay", "io_error",
    "barrier_stall", "serving_request", "decode_stall", "replica_kill",
    "kv_page_leak", "spec_draft_poison", "sentinel_injection",
    "sentinel_injection_window", "cache_corrupt", "data_stall",
    "shard_corrupt", "mem_pressure_bytes", "straggler_delay",
    "current_step", "KILL_EXIT_CODE",
]

#: exit code of an injected kill — 128+9, what a real SIGKILL reports
KILL_EXIT_CODE = 137


class InjectedFault(BaseException):
    """Raise-mode crash.  A BaseException on purpose: recovery code that
    catches ``Exception`` must treat an injected crash like a real process
    death, not swallow it."""


class FaultPlan:
    """One armed fault scenario.  All fields optional; ``None``/0 disarms
    the corresponding fault."""

    def __init__(self, kill_step: Optional[int] = None,
                 ckpt_crash: Optional[str] = None,
                 ckpt_poison_serial: Optional[int] = None,
                 io_delay_ms: float = 0.0,
                 nan_var: Optional[str] = None, nan_step: int = 0,
                 grad_inf_step: Optional[int] = None,
                 grad_inf_value: float = float("inf"),
                 loss_spike_step: Optional[int] = None,
                 loss_spike_factor: float = 1e4,
                 barrier_stall_s: float = 0.0,
                 serve_delay_ms: float = 0.0, serve_fail_every: int = 0,
                 decode_stall_ms: float = 0.0,
                 kv_page_leak: Optional[int] = None,
                 spec_draft_poison: Optional[int] = None,
                 replica_kill_after: Optional[int] = None,
                 cache_corrupt: bool = False,
                 data_stall_ms: float = 0.0,
                 data_stall_at: Optional[int] = None,
                 shard_corrupt: bool = False,
                 mem_pressure_mb: float = 0.0,
                 mem_pressure_at: int = 8,
                 straggler_rank: Optional[int] = None,
                 straggler_ms: float = 0.0,
                 host_loss_rank: Optional[int] = None,
                 host_loss_at_step: int = 0,
                 io_error_rate: float = 0.0, io_error_seed: int = 0,
                 rank: Optional[int] = None, mode: str = "exit"):
        if ckpt_crash not in (None, "before", "after"):
            raise ValueError(
                f"ckpt_crash must be 'before' or 'after' (the _SUCCESS "
                f"marker), got {ckpt_crash!r}")
        if mode not in ("exit", "raise"):
            raise ValueError(f"mode must be 'exit' or 'raise', got {mode!r}")
        self.kill_step = None if kill_step is None else int(kill_step)
        self.ckpt_crash = ckpt_crash
        self.ckpt_poison_serial = None if ckpt_poison_serial is None \
            else int(ckpt_poison_serial)
        self.io_delay_ms = float(io_delay_ms)
        self.nan_var = nan_var
        self.nan_step = int(nan_step)
        self.grad_inf_step = None if grad_inf_step is None else int(grad_inf_step)
        self.grad_inf_value = float(grad_inf_value)
        self.loss_spike_step = None if loss_spike_step is None \
            else int(loss_spike_step)
        self.loss_spike_factor = float(loss_spike_factor)
        self.barrier_stall_s = float(barrier_stall_s)
        self.serve_delay_ms = float(serve_delay_ms)
        self.serve_fail_every = int(serve_fail_every)
        self.decode_stall_ms = float(decode_stall_ms)
        self.kv_page_leak = None if kv_page_leak is None \
            else int(kv_page_leak)
        self.spec_draft_poison = None if spec_draft_poison is None \
            else int(spec_draft_poison)
        self.replica_kill_after = None if replica_kill_after is None \
            else int(replica_kill_after)
        self.cache_corrupt = bool(cache_corrupt)
        self.data_stall_ms = float(data_stall_ms)
        self.data_stall_at = None if data_stall_at is None \
            else int(data_stall_at)
        self.shard_corrupt = bool(shard_corrupt)
        self.mem_pressure_mb = float(mem_pressure_mb)
        self.mem_pressure_at = int(mem_pressure_at)
        self.straggler_rank = None if straggler_rank is None \
            else int(straggler_rank)
        self.straggler_ms = float(straggler_ms)
        self.host_loss_rank = None if host_loss_rank is None \
            else int(host_loss_rank)
        self.host_loss_at_step = int(host_loss_at_step)
        self.io_error_rate = float(io_error_rate)
        self.io_error_seed = int(io_error_seed)
        self.rank = None if rank is None else int(rank)
        self.mode = mode
        # one-shot disarm state
        self._io_error_attempts: dict = {}
        self._replica_kill_fired = False
        self._nan_fired = False
        self._stall_fired = False
        self._serve_count = 0
        self._data_stall_fired = False
        self._shard_corrupt_fired = False
        self._mem_pressure_calls = 0
        self._kv_leaks_left = 0 if self.kv_page_leak is None \
            else self.kv_page_leak

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultPlan"]:
        """Parse the PADDLE_FAULT_* contract; None when nothing is armed.

        Every knob is read through the envcontract registry's typed
        parser (ISSUE 18 satellite): the declaration in
        ``fluid.envcontract`` — name, type, default — is the single
        source of truth the chaos schedule auto-discovers from and
        ``repo_lint`` enforces, so an undeclared fault knob cannot be
        consumed here.  ``env`` may be any mapping (the supervisor's
        per-worker dicts in tests); the default is the live process
        environment."""
        env = os.environ if env is None else env
        if not any(k.startswith("PADDLE_FAULT_") and (v or "").strip()
                   for k, v in env.items()):
            return None
        from . import envcontract as _ec

        def val(name):
            knob = _ec.REGISTRY[name]  # KeyError = undeclared: on purpose
            return knob.parse(env.get(name))

        return cls(
            kill_step=val("PADDLE_FAULT_KILL_STEP"),
            ckpt_crash=val("PADDLE_FAULT_CKPT_CRASH"),
            ckpt_poison_serial=val("PADDLE_FAULT_CKPT_POISON_SERIAL"),
            io_delay_ms=val("PADDLE_FAULT_IO_DELAY_MS"),
            nan_var=val("PADDLE_FAULT_NAN_VAR"),
            nan_step=val("PADDLE_FAULT_NAN_STEP"),
            grad_inf_step=val("PADDLE_FAULT_GRAD_INF_STEP"),
            grad_inf_value=val("PADDLE_FAULT_GRAD_INF_VALUE"),
            loss_spike_step=val("PADDLE_FAULT_LOSS_SPIKE_STEP"),
            loss_spike_factor=val("PADDLE_FAULT_LOSS_SPIKE_FACTOR"),
            barrier_stall_s=val("PADDLE_FAULT_BARRIER_STALL"),
            serve_delay_ms=val("PADDLE_FAULT_SERVE_DELAY_MS"),
            serve_fail_every=val("PADDLE_FAULT_SERVE_FAIL_EVERY"),
            decode_stall_ms=val("PADDLE_FAULT_DECODE_STALL_MS"),
            kv_page_leak=val("PADDLE_FAULT_KV_PAGE_LEAK"),
            spec_draft_poison=val("PADDLE_FAULT_SPEC_DRAFT_POISON"),
            replica_kill_after=val("PADDLE_FAULT_REPLICA_KILL_AFTER"),
            cache_corrupt=val("PADDLE_FAULT_CACHE_CORRUPT"),
            data_stall_ms=val("PADDLE_FAULT_DATA_STALL_MS"),
            data_stall_at=val("PADDLE_FAULT_DATA_STALL_AT"),
            shard_corrupt=val("PADDLE_FAULT_SHARD_CORRUPT"),
            mem_pressure_mb=val("PADDLE_FAULT_MEM_PRESSURE"),
            mem_pressure_at=val("PADDLE_FAULT_MEM_PRESSURE_AT"),
            straggler_rank=val("PADDLE_FAULT_STRAGGLER_RANK"),
            straggler_ms=val("PADDLE_FAULT_STRAGGLER_MS"),
            host_loss_rank=val("PADDLE_FAULT_HOST_LOSS_RANK"),
            host_loss_at_step=val("PADDLE_FAULT_HOST_LOSS_AT_STEP"),
            io_error_rate=val("PADDLE_FAULT_IO_ERROR_RATE"),
            io_error_seed=val("PADDLE_FAULT_IO_ERROR_SEED"),
            rank=val("PADDLE_FAULT_RANK"),
            mode=val("PADDLE_FAULT_MODE"),
        )

    # -- firing --
    def _applies_to_this_rank(self) -> bool:
        if self.rank is None:
            return True
        return self.rank == int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    def _crash(self, what: str):
        if self.mode == "raise":
            raise InjectedFault(what)
        from .log import LOG

        LOG(f"fault: injected crash ({what}) — exiting {KILL_EXIT_CODE}")
        os._exit(KILL_EXIT_CODE)


# module state: the armed plan (None = nothing armed; _UNSET = env not yet
# consulted, so subprocesses that set PADDLE_FAULT_* before first use are
# honored without an import-order dependency) and the step counter
_UNSET = object()
_plan = _UNSET
_step = 0


def install(plan: Optional[FaultPlan]) -> None:
    """Arm a plan programmatically (overrides the env)."""
    global _plan, _step
    _plan = plan
    _step = 0


def clear() -> None:
    """Disarm everything, including any env-derived plan."""
    install(None)


def active() -> Optional[FaultPlan]:
    global _plan
    if _plan is _UNSET:
        _plan = FaultPlan.from_env()
    return _plan


def current_step() -> int:
    return _step


def _host_loss_fire(plan: FaultPlan, lo: int, hi: int) -> None:
    """Permanent-host-loss oracle: when the armed rank reaches its step,
    drop a ``host_lost_g<gen>_r<rank>`` marker into the supervisor's
    heartbeat dir (the survivor census input — this "host" never
    rejoins) and crash hard.  Keyed on ``host_loss_rank`` alone, like the
    straggler, so it composes with PADDLE_FAULT_RANK-scoped faults."""
    if plan.host_loss_rank is None:
        return
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    if plan.host_loss_rank != rank:
        return
    if not lo <= plan.host_loss_at_step < hi:
        return
    hb_dir = os.environ.get("PADDLE_ELASTIC_HB_DIR")
    if hb_dir:
        gen = os.environ.get("PADDLE_ELASTIC_GENERATION", "0") or "0"
        marker = os.path.join(hb_dir, f"host_lost_g{gen}_r{rank}")

        def _write_marker():
            os.makedirs(hb_dir, exist_ok=True)
            with open(marker, "w") as f:
                f.write(str(time.time()))

        try:
            from .retry import retry_io

            # the census marker is the survivor count's only input — a
            # transient blip here must not silently shrink the record
            retry_io(_write_marker, what="census.host_lost")
        except OSError:
            pass  # the crash below still fires; census just sees a kill
    plan._crash(
        f"host loss (rank {rank}) at step {plan.host_loss_at_step}")


def on_step(step: Optional[int] = None) -> int:
    """Training-step boundary, called BEFORE the step executes.  ``step``
    pins the index explicitly (resume-aware callers); default is an
    internal monotonic per-process counter.  Fires kill-at-step-N and
    the permanent host-loss fault."""
    global _step
    if step is not None:
        _step = int(step)
    plan = active()
    if plan is not None:
        if plan.kill_step is not None and _step == plan.kill_step \
                and plan._applies_to_this_rank():
            plan._crash(f"kill at step {_step}")
        _host_loss_fire(plan, _step, _step + 1)
    fired = _step
    if step is None:
        _step += 1
    else:
        _step = int(step) + 1
    return fired


def advance(n: int) -> None:
    """Bulk step advance for fused multi-step dispatches (run_steps): a
    kill (or host loss) armed anywhere inside the window fires before
    the dispatch — the finest granularity a single XLA dispatch
    allows."""
    global _step
    plan = active()
    if plan is not None:
        if plan.kill_step is not None \
                and _step <= plan.kill_step < _step + n \
                and plan._applies_to_this_rank():
            plan._crash(f"kill inside step window [{_step}, {_step + n})")
        _host_loss_fire(plan, _step, _step + n)
    _step += n


def corrupt_state(named_vals: dict) -> dict:
    """NaN-poison the armed var once its step arrives (one-shot).  Called
    with a step's new state; returns it (possibly rewritten).  The injected
    NaN then flows into the scope exactly like a real numerical blow-up, so
    check_nan_inf / supervisor NaN policies see the genuine article."""
    plan = active()
    if plan is None or plan.nan_var is None or plan._nan_fired \
            or _step <= plan.nan_step or not plan._applies_to_this_rank():
        return named_vals
    if plan.nan_var in named_vals:
        import numpy as np

        val = named_vals[plan.nan_var]
        poisoned = np.asarray(val, dtype=np.result_type(val, np.float32))
        poisoned = np.full_like(poisoned, np.nan)
        named_vals = dict(named_vals)
        named_vals[plan.nan_var] = poisoned
        plan._nan_fired = True
    return named_vals


def sentinel_injection(step: int):
    """Per-step numerics-fault multipliers for the guardian's sentinel:
    ``(seed_mul, loss_mul)``, both 1.0 when nothing is armed for ``step``.

    ``seed_mul`` scales the backward seed IN-GRAPH (the @LOSS_SEED_MUL@
    entry the guarded executor step feeds into the tagged __loss_seed__
    op), so a grad-Inf injection flows through the real gradient ops and
    a dumped replay bundle reproduces it bit-for-bit.  ``loss_mul``
    scales the observed loss (the corrupt-batch spike oracle).  Keyed on
    exact step equality, so the injection is naturally one-shot per step
    and a resumed run that re-executes the step re-fires it — which is
    what a deterministic oracle should do."""
    plan = active()
    if plan is None or not plan._applies_to_this_rank():
        return 1.0, 1.0
    seed_mul = plan.grad_inf_value \
        if plan.grad_inf_step == step else 1.0
    loss_mul = plan.loss_spike_factor \
        if plan.loss_spike_step == step else 1.0
    return seed_mul, loss_mul


def sentinel_injection_window(start: int, n_steps: int):
    """Vectorized :func:`sentinel_injection` for a fused ``run_steps``
    window: ``(seed_mul, loss_mul)`` float32 arrays of shape ``(n_steps,)``
    covering absolute steps ``[start, start + n_steps)``.  The guarded scan
    consumes slice ``i`` at window step ``i``, so a grad-Inf armed at an
    absolute step inside the window fires at exactly that step of the
    scanned loop — same determinism contract as the per-step path."""
    import numpy as np

    seed = np.ones(n_steps, np.float32)
    loss = np.ones(n_steps, np.float32)
    plan = active()
    if plan is not None and plan._applies_to_this_rank():
        if plan.grad_inf_step is not None \
                and start <= plan.grad_inf_step < start + n_steps:
            seed[plan.grad_inf_step - start] = plan.grad_inf_value
        if plan.loss_spike_step is not None \
                and start <= plan.loss_spike_step < start + n_steps:
            loss[plan.loss_spike_step - start] = plan.loss_spike_factor
    return seed, loss


def ckpt_crash_point(where: str) -> None:
    """Checkpoint-save crash hook; ``where`` is 'before' or 'after' the
    _SUCCESS marker write."""
    plan = active()
    if plan is not None and plan.ckpt_crash == where \
            and plan._applies_to_this_rank():
        plan._crash(f"checkpoint crash {where} _SUCCESS")


def ckpt_poison(serial: int, dirname: str) -> bool:
    """Committed-but-bad checkpoint oracle: when ``ckpt_poison_serial``
    matches ``serial``, rewrite every float array file under ``dirname``
    as all-NaN IN PLACE, before the caller commits its _SUCCESS marker.
    Unlike :func:`ckpt_crash_point`, the serial ends up fully committed
    and structurally valid — the watcher/loader trusts it, only the
    serving canary's output-sanity sentinel can catch it (the
    deterministic trigger for hot-swap auto-rollback).  Walks the dir
    recursively so sharded serials (``shard_*/``) are poisoned too;
    integer arrays and unparseable files are left intact.  Returns True
    when it fired."""
    plan = active()
    if plan is None or plan.ckpt_poison_serial is None \
            or plan.ckpt_poison_serial != int(serial) \
            or not plan._applies_to_this_rank():
        return False
    import numpy as np

    fired = False
    for root, _dirs, files in os.walk(dirname):
        for fname in files:
            path = os.path.join(root, fname)
            try:
                arr = np.load(path, allow_pickle=False)
            except Exception:
                continue  # markers / manifests / non-npy payloads
            if not np.issubdtype(arr.dtype, np.floating):
                continue
            with open(path, "wb") as f:
                np.save(f, np.full_like(arr, np.nan), allow_pickle=False)
            fired = True
    if fired:
        from .log import LOG

        LOG(f"fault: NaN-poisoned checkpoint serial {serial} at {dirname}")
    return fired


def io_delay() -> None:
    """Slow-storage simulation: sleep inside checkpoint write paths."""
    plan = active()
    if plan is not None and plan.io_delay_ms > 0 \
            and plan._applies_to_this_rank():
        time.sleep(plan.io_delay_ms / 1000.0)


def _io_error_key(path: str) -> str:
    """Stable identity for a file across runs: the path's last two
    components (``checkpoint_0/fc_0.w_0``, ``heartbeats/hb_1``) — the
    enclosing temp/work dir differs per run, the tail does not, so the
    SAME logical files fail under the same seed in every drill."""
    parts = [p for p in os.path.normpath(path).split(os.sep) if p]
    return "/".join(parts[-2:])


def io_error(path: str, op: str) -> None:
    """Deterministic transient-I/O oracle, consulted immediately before
    each raw read/write of durable state (checkpoint var files, _SUCCESS
    commits, census heartbeats/markers, warmup manifests, compile-cache
    commits).  A seeded hash of ``(seed, path tail, op)`` picks the
    fraction ``io_error_rate`` of keys that fail; for a picked key the
    FIRST attempt raises OSError and every later attempt succeeds —
    transient by construction, so bounded retry (``fluid.retry.
    retry_io``) always recovers while an unretried site sees a hard
    failure.  Content corruption never flows through here: a torn or
    bit-rotted payload surfaces as ValueError at parse time and keeps
    taking the serial-condemnation fallback, never the retry path."""
    plan = active()
    if plan is None or plan.io_error_rate <= 0 \
            or not plan._applies_to_this_rank():
        return
    import hashlib

    key = (_io_error_key(path), str(op))
    digest = hashlib.sha1(
        f"{plan.io_error_seed}|{key[0]}|{key[1]}".encode()).hexdigest()
    if int(digest[:8], 16) / float(0xFFFFFFFF) >= plan.io_error_rate:
        return
    attempts = plan._io_error_attempts.get(key, 0)
    plan._io_error_attempts[key] = attempts + 1
    if attempts == 0:
        raise OSError(
            f"injected transient I/O error ({key[1]} {key[0]}, "
            f"attempt 1 — retry succeeds)")


def serving_request() -> None:
    """Serving-path hook, called once per admitted request at batch
    formation.  Applies the per-request injected delay, then fails every
    Nth request by RAISING InjectedFault — always a raise regardless of
    ``mode``, because a per-request fault models a failed request, not a
    dead server (the engine delivers it on that request's future and the
    rest of the batch must still complete)."""
    plan = active()
    if plan is None or not plan._applies_to_this_rank():
        return
    if plan.serve_delay_ms > 0:
        time.sleep(plan.serve_delay_ms / 1000.0)
    if plan.serve_fail_every > 0:
        plan._serve_count += 1
        if plan._serve_count % plan.serve_fail_every == 0:
            raise InjectedFault(
                f"injected serving failure (request #{plan._serve_count})")


def decode_stall(n_ticks: int = 1) -> None:
    """Continuous-batching tick stall: the DecodeEngine worker calls this
    once per iteration (admit -> step -> retire), so an armed stall
    inflates EVERY in-flight stream's inter-token latency by the same
    deterministic amount — the oracle for the SLO watchdog breaching on
    ``serving.intertoken_s`` (unlike SERVE_DELAY_MS, which delays whole
    requests at batch formation, this models a slow decode step)."""
    plan = active()
    if plan is None or plan.decode_stall_ms <= 0 \
            or not plan._applies_to_this_rank():
        return
    time.sleep(plan.decode_stall_ms * max(1, int(n_ticks)) / 1000.0)


def replica_kill(served_total: int) -> bool:
    """Serving-fleet replica-death oracle, consulted by the fleet after
    every completed request with the fleet-wide served total.  True
    EXACTLY ONCE, when the total first reaches ``replica_kill_after`` —
    the fleet then kills the replica that served that request (its
    resident futures fail, the pool census re-spawns it on surviving
    devices).  Deliberately never a process exit, whatever ``mode`` says:
    the fault models a dead replica inside a living fleet, and an
    ``os._exit`` would take the router and every other replica with it."""
    plan = active()
    if plan is None or plan.replica_kill_after is None \
            or plan._replica_kill_fired \
            or not plan._applies_to_this_rank():
        return False
    if int(served_total) < plan.replica_kill_after:
        return False
    plan._replica_kill_fired = True
    from .log import LOG

    LOG(f"fault: replica kill after {served_total} served requests")
    return True


def kv_page_leak() -> bool:
    """Paged-KV leak oracle, consulted by ``serving.kvpool.PagePool``
    once per page free: True for the first ``kv_page_leak`` calls
    (decrementing — one skipped free per True), then permanently False.
    A True return makes the allocator SKIP that free, so the page stays
    accounted live forever: the deterministic paged twin of the
    MEM_PRESSURE synthetic leak, visible in ``kvpool.pages_free`` /
    ``kvpool.hbm_bytes`` and the live-buffer ledger."""
    plan = active()
    if plan is None or plan._kv_leaks_left <= 0 \
            or not plan._applies_to_this_rank():
        return False
    plan._kv_leaks_left -= 1
    return True


def spec_draft_poison() -> Optional[int]:
    """Speculative-draft poison oracle, consulted by ``serving.specdec``
    once per spec tick: the armed tick threshold, or None when disarmed.
    From engine tick >= threshold the SpecDecoder replaces every drafted
    token with deterministic garbage, collapsing acceptance to ~1/vocab.
    Proves two things at once: the adaptive controller fires
    ``specdec.fallback`` within its window, and the emitted stream stays
    bitwise correct anyway (acceptance only ever keeps target argmaxes,
    so a garbage draft costs throughput, never correctness)."""
    plan = active()
    if plan is None or plan.spec_draft_poison is None \
            or not plan._applies_to_this_rank():
        return None
    return plan.spec_draft_poison


def cache_corrupt() -> bool:
    """Compile-cache read-corruption oracle: when armed, every persistent
    cache entry load is treated as corrupt, forcing the fresh-compile
    fallback (``CompileCacheStore.get`` quarantines the entry and reports
    a miss; the run must still succeed).  Deterministic by construction —
    the hook is consulted at every load, so a run under this flag
    exercises the fallback path on every single lookup."""
    plan = active()
    return (plan is not None and plan.cache_corrupt
            and plan._applies_to_this_rank())


def data_stall(index: int) -> None:
    """Input-pipeline stall injection, consulted by the pipeline source
    once per pulled sample (``index`` is the source's epoch cursor).
    With ``data_stall_at`` unset the stall applies to EVERY sample (a
    constantly slow reader); with it set, the stall fires exactly once,
    at that cursor — the deterministic oracle for the data-wait SLO
    (one window's ``train.data_wait_s`` spikes, the watchdog breaches)."""
    plan = active()
    if plan is None or plan.data_stall_ms <= 0 \
            or not plan._applies_to_this_rank():
        return
    if plan.data_stall_at is None:
        time.sleep(plan.data_stall_ms / 1000.0)
    elif not plan._data_stall_fired and int(index) == plan.data_stall_at:
        plan._data_stall_fired = True
        time.sleep(plan.data_stall_ms / 1000.0)


def shard_corrupt() -> bool:
    """Data-state corruption oracle: True exactly once when armed — the
    next ``data_state`` blob write is truncated mid-payload, so the
    resumed run must detect the corrupt cursor at load time and fall
    back to the previous complete serial (never resume at a garbage
    position)."""
    plan = active()
    if plan is None or not plan.shard_corrupt or plan._shard_corrupt_fired \
            or not plan._applies_to_this_rank():
        return False
    plan._shard_corrupt_fired = True
    return True


def mem_pressure_bytes() -> int:
    """Synthetic-leak oracle, consulted by the live-buffer ledger once per
    observation: zero until the ``mem_pressure_at``-th call, then
    ``mem_pressure_mb`` MB doubling per observation — deterministic
    monotonic growth that trips the SLO watchdog's factor-over-median
    breach (and, with ``PADDLE_MEM_BUDGET_MB`` set, the over-budget
    event) within a few windows, like a real accumulating leak."""
    plan = active()
    if plan is None or plan.mem_pressure_mb <= 0 \
            or not plan._applies_to_this_rank():
        return 0
    plan._mem_pressure_calls += 1
    past = plan._mem_pressure_calls - plan.mem_pressure_at
    if past <= 0:
        return 0
    return int(plan.mem_pressure_mb * (1 << 20)) << min(past - 1, 16)


def straggler_delay(n_steps: int = 1) -> None:
    """Straggler oracle: the armed rank sleeps ``straggler_ms`` per step
    at the training step boundary (a fused window sleeps once for its
    whole span).  Deliberately keyed on ``straggler_rank`` alone —
    ``PADDLE_FAULT_RANK`` scopes the OTHER faults, so a kill on rank 0
    and a straggler on rank 1 compose in one scenario."""
    plan = active()
    if plan is None or plan.straggler_ms <= 0:
        return
    if plan.straggler_rank is not None and plan.straggler_rank != \
            int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0):
        return
    time.sleep(plan.straggler_ms * max(1, int(n_steps)) / 1000.0)


def barrier_stall(tag: str = "") -> None:
    """Wedged-collective simulation: one-shot sleep before a barrier, long
    enough for the supervisor's heartbeat timeout to classify this process
    as wedged."""
    plan = active()
    if plan is not None and plan.barrier_stall_s > 0 \
            and not plan._stall_fired and plan._applies_to_this_rank():
        plan._stall_fired = True
        time.sleep(plan.barrier_stall_s)
