"""Decoder DSL (ref: python/paddle/fluid/contrib/decoder/)."""

from . import beam_search_decoder
from .beam_search_decoder import (BeamSearchDecoder, InitState,
                                  JitBeamSearchDecoder, StateCell,
                                  TrainingDecoder)

__all__ = ["beam_search_decoder", "InitState", "StateCell",
           "TrainingDecoder", "BeamSearchDecoder", "JitBeamSearchDecoder"]
