"""User-facing seq2seq decoder DSL: InitState / StateCell / TrainingDecoder /
BeamSearchDecoder (ref: python/paddle/fluid/contrib/decoder/
beam_search_decoder.py:43,159,384,523 — same public API).

A StateCell describes an RNN cell abstractly: named step inputs, named
hidden states with their initializers, and a user-supplied updater that maps
(inputs, states) -> new states.  The SAME cell then drives two execution
harnesses:

 - TrainingDecoder: teacher-forced unrolling over a LoD step input, backed
   by layers.DynamicRNN (states live in rnn memories, outputs become a
   packed LoDTensor);
 - BeamSearchDecoder: a While generation loop, where states live in tensor
   arrays indexed by the step counter and each step expands hypotheses with
   layers.beam_search, terminating early once every beam emits end_id.

TPU note: BeamSearchDecoder's generation loop is data-dependent (live beam
widths change shape), so the executor runs it as eager islands between
jitted segments (fluid/executor.py) — the reference runs the same
structure as host-side while/array ops around device kernels.  The
TPU-native path is JitBeamSearchDecoder below: the SAME StateCell, but the
whole loop compiles to ONE lax.while_loop XLA program with static
[batch, beam] shapes (ops/beam_search_jit.py) — prefer it for generation
throughput; keep BeamSearchDecoder for multi-hypothesis warm starts or
cells with data-dependent host ops.
"""

from __future__ import annotations

import contextlib

import numpy as np

from ... import layers, unique_name
from ...framework import Variable
from ...layer_helper import LayerHelper

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder",
           "JitBeamSearchDecoder"]

_TRAINING, _BEAM, _JIT = "training", "beam_search", "jit_beam_search"


def _loop_array(helper, init, zero_idx):
    """Create a tensor array holding ``init`` at index 0, with BOTH the
    create and the init write placed in the block ENCLOSING the current
    (While-body) block: loop-carried arrays must exist before the first
    iteration reads them."""
    from ... import core

    program = helper.main_program
    parent_idx = program.current_block().parent_idx
    block = program.block(parent_idx) if parent_idx >= 0 \
        else program.current_block()
    array = block.create_var(
        name=unique_name.generate("beam_decoder_array"),
        dtype=init.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
    if getattr(init, "shape", None) is not None:
        array.shape = tuple(init.shape)
    block.append_op(type="write_to_array",
                    inputs={"X": [init], "I": [zero_idx]},
                    outputs={"Out": [array]})
    return array


class InitState:
    """Initial value of one hidden state (ref :43).  Either an explicit
    ``init`` Variable, or a constant tensor shaped like ``init_boot``."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is not None:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        else:
            raise ValueError(
                "InitState needs `init` or `init_boot` to determine shape")
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _RnnMemoryBacking:
    """State storage inside a TrainingDecoder: a DynamicRNN memory."""

    def __init__(self, rnn, init_state: InitState):
        self._rnn = rnn
        self._mem = rnn.memory(init=init_state.value,
                               need_reorder=init_state.need_reorder)

    def current(self):
        return self._mem

    def commit(self, new_value):
        self._rnn.update_memory(self._mem, new_value)


class _ArrayBacking:
    """State storage inside a BeamSearchDecoder: a tensor array indexed by
    the decoder's own step counter (written at counter+1 each step)."""

    def __init__(self, decoder, init_state: InitState):
        self._decoder = decoder
        self._array = _loop_array(decoder._helper, init_state.value,
                                  decoder._zero_idx)

    def current(self):
        return layers.array_read(array=self._array,
                                 i=self._decoder._counter)

    def commit(self, new_value):
        self._decoder._deferred_writes.append((new_value, self._array))


class StateCell:
    """Abstract RNN cell: named inputs + named states + an updater
    (ref :159).  ``out_state`` names the state whose value scores tokens."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper("state_cell", name=name)
        for v in states.values():
            if not isinstance(v, InitState):
                raise ValueError("every state must be an InitState")
        if out_state not in states:
            raise ValueError(f"out_state {out_state!r} not among states")
        self._init_states = dict(states)
        self._inputs = dict(inputs)
        self._out_state = out_state
        self._updater = None
        self._decoder = None
        self._backings = {}
        self._cur = {}

    # -- decoder attach/detach (TrainingDecoder/BeamSearchDecoder call these)
    def _enter_decoder(self, decoder):
        if self._decoder is not None:
            raise ValueError("StateCell is already attached to a decoder")
        self._decoder = decoder
        self._backings = {}
        self._cur = {}

    def _leave_decoder(self, decoder):
        if self._decoder is not decoder:
            raise ValueError("StateCell attached to a different decoder")
        self._decoder = None

    def _materialize(self):
        """Lazily create per-decoder state storage and read current values."""
        if self._backings or self._decoder is None:
            return
        for name, init in self._init_states.items():
            b = self._decoder._make_backing(name, init)
            self._backings[name] = b
            self._cur[name] = b.current()

    # -- user surface
    def get_state(self, state_name):
        self._materialize()
        if state_name not in self._cur:
            raise ValueError(f"unknown state {state_name!r}")
        return self._cur[state_name]

    def get_input(self, input_name):
        v = self._inputs.get(input_name)
        if v is None:
            raise ValueError(f"input {input_name!r} has not been provided")
        return v

    def set_state(self, state_name, state_value):
        self._cur[state_name] = state_value

    def state_updater(self, updater):
        """Decorator registering fn(state_cell) that computes new states via
        get_input/get_state + set_state."""
        self._updater = updater
        return updater

    def compute_state(self, inputs):
        self._materialize()
        for name, value in inputs.items():
            if name not in self._inputs:
                raise ValueError(f"unknown input {name!r}")
            self._inputs[name] = value
        if self._updater is None:
            raise ValueError("no state_updater registered")
        self._updater(self)

    def update_states(self):
        for name, backing in self._backings.items():
            backing.commit(self._cur[name])

    def out_state(self):
        return self._cur[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoder over a LoD target sequence (ref :384);
    a thin harness around layers.DynamicRNN driven by a StateCell."""

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._rnn = layers.DynamicRNN()
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._done = False

    type = _TRAINING

    def _make_backing(self, name, init_state):
        return _RnnMemoryBacking(self._rnn, init_state)

    @property
    def dynamic_rnn(self):
        return self._rnn

    @property
    def state_cell(self):
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        with self._rnn.block():
            yield
        self._done = True
        self._state_cell._leave_decoder(self)

    def step_input(self, x):
        return self._rnn.step_input(x)

    def static_input(self, x):
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if not self._done:
            raise ValueError("visit TrainingDecoder output after block()")
        return self._rnn(*args, **kwargs)


class BeamSearchDecoder:
    """Generation-time beam search harness (ref :523).

    ``decode()`` builds the canonical loop: read back last step's live
    hypotheses, expand cell states to the live beam width
    (sequence_expand over the scores' LoD), advance the cell one step,
    project ``out_state`` to vocab scores, pick beam_size survivors with
    layers.beam_search, and stop early when every beam has ended.  Override
    decode() for a custom loop; __call__ backtracks the full hypotheses
    with layers.beam_search_decode."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._beam_size = beam_size
        self._end_id = end_id

        self._counter = layers.zeros(shape=[1], dtype="int64")
        self._counter.stop_gradient = True
        self._zero_idx = layers.fill_constant(shape=[1], dtype="int64",
                                              value=0, force_cpu=True)
        self._max_len = layers.fill_constant(shape=[1], dtype="int64",
                                             value=max_len)
        self._cond = layers.less_than(x=self._counter, y=self._max_len)
        self._while = layers.While(self._cond)
        self._deferred_writes = []
        self._tracked = {}     # read-value name -> backing array
        self._ids_array = None
        self._scores_array = None
        self._done = False
        self._state_cell._enter_decoder(self)

    type = _BEAM

    def _make_backing(self, name, init_state):
        return _ArrayBacking(self, init_state)

    @property
    def state_cell(self):
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        """One While iteration; deferred array writes land at counter+1 so
        the next iteration reads this step's survivors."""
        with self._while.block():
            yield
            with layers.Switch() as switch:
                with switch.case(self._cond):
                    layers.increment(x=self._counter, value=1,
                                     in_place=True)
                    for value, array in self._deferred_writes:
                        layers.array_write(x=value, i=self._counter,
                                           array=array)
                    layers.less_than(x=self._counter, y=self._max_len,
                                     cond=self._cond)
        self._done = True
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        layers.fill_constant(shape=[1], value=0, dtype="bool",
                             force_cpu=True, out=self._cond)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Array-backed loop variable: initialized before the loop, read at
        the counter, rewritten via update_array each live step."""
        if is_ids and is_scores:
            raise ValueError("an array is either ids or scores, not both")
        if not isinstance(init, Variable):
            raise TypeError("read_array init must be a Variable")
        array = _loop_array(self._helper, init, self._zero_idx)
        if is_ids:
            self._ids_array = array
        elif is_scores:
            self._scores_array = array
        value = layers.array_read(array=array, i=self._counter)
        self._tracked[value.name] = array
        return value

    def update_array(self, array, value):
        backing = self._tracked.get(array.name)
        if backing is None:
            raise ValueError("update_array target was not read_array'd")
        self._deferred_writes.append((value, backing))

    def decode(self):
        cell = self._state_cell
        with self.block():
            prev_ids = self.read_array(init=self._init_ids, is_ids=True)
            prev_scores = self.read_array(init=self._init_scores,
                                          is_scores=True)
            prev_emb = layers.embedding(
                prev_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb)

            feeds = {}
            tracked_inputs = {}
            for name, var in self._input_var_dict.items():
                if name not in cell._inputs:
                    raise ValueError(
                        f"input_var_dict key {name!r} unknown to the cell")
                stored = self.read_array(init=var)
                tracked_inputs[name] = stored
                feeds[name] = layers.sequence_expand(stored, prev_scores)
            for name in cell._inputs:
                if name not in feeds:
                    feeds[name] = prev_emb
            # live beam width changes step to step: stretch every state
            # over the current hypotheses (parents repeat per child)
            for sname in cell._init_states:
                cell.set_state(
                    sname,
                    layers.sequence_expand(cell.get_state(sname),
                                           prev_scores))

            cell.compute_state(inputs=feeds)
            out = layers.lod_reset(x=cell.out_state(), y=prev_scores)
            scores = layers.fc(input=out, size=self._target_dict_dim,
                               act="softmax")
            topk_scores, topk_indices = layers.topk(scores,
                                                    k=self._topk_size)
            accu = layers.elementwise_add(
                x=layers.log(topk_scores),
                y=layers.reshape(prev_scores, shape=[-1]), axis=0)
            sel_ids, sel_scores = layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu,
                self._beam_size, end_id=self._end_id, level=0)

            with layers.Switch() as switch:
                with switch.case(layers.is_empty(sel_ids)):
                    self.early_stop()
                with switch.default():
                    cell.update_states()
                    self.update_array(prev_ids, sel_ids)
                    self.update_array(prev_scores, sel_scores)
                    for name, stored in tracked_inputs.items():
                        self.update_array(stored, feeds[name])

    def __call__(self):
        if not self._done:
            raise ValueError("run decode() (or block()) before calling")
        return layers.beam_search_decode(ids=self._ids_array,
                                         scores=self._scores_array,
                                         beam_size=self._beam_size,
                                         end_id=self._end_id)


class _JitBacking:
    """State storage inside a JitBeamSearchDecoder: a placeholder variable
    in the step sub-block.  The jit_beam_search executor handler feeds it
    each lax.while_loop iteration and reads the committed output name."""

    def __init__(self, decoder, name, init_state: InitState):
        init = init_state.value
        shape = (-1,) + tuple(init.shape[1:]) if init.shape else (-1,)
        self._ph = decoder._step_block.create_var(
            name=unique_name.generate(f"jbs_state_{name}"),
            dtype=init.dtype, shape=shape)
        self._decoder = decoder
        self._name = name
        decoder._register_state(name, init, self._ph)

    def current(self):
        return self._ph

    def commit(self, new_value):
        self._decoder._commit_state(self._name, new_value)


class JitBeamSearchDecoder:
    """TPU-native generation harness: the SAME StateCell as
    BeamSearchDecoder, but the whole loop compiles to ONE XLA program.

    Where BeamSearchDecoder builds a While program (one host iteration per
    step, per-op dispatches — the reference's structure,
    ref: beam_search_op.cc:24 / beam_search_decode_op.cc),
    ``decode()`` here builds the cell's single step into a sub-block of
    placeholder variables and appends one ``jit_beam_search`` op that runs
    it under ``lax.while_loop`` with static [batch, beam] state and a
    finished-mask early exit (ops/beam_search_jit.py).  ``__call__``
    returns the same 2-level-LoD (ids, scores) pair as BeamSearchDecoder —
    the LoD packaging is the single eager boundary op.

    Contract notes:
     - every source sentence decodes ``beam_size`` hypotheses (the eager
       op is fixed-width too, so results agree — see the oracle test);
     - per-sentence tensors the cell consumes (encoder context) must be
       passed via ``input_var_dict``; they are tiled beam-wide ONCE,
       outside the loop (the eager path re-expands per step instead);
     - the cell updater must use only jit-traceable layers (no
       data-dependent host ops) — true for every standard RNN/attention
       cell.
    """

    type = _JIT

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        # topk_size/sparse_emb accepted for BeamSearchDecoder signature
        # parity: global top-k over beam*vocab subsumes the per-beam
        # topk_size prefilter whenever beam_size <= topk_size, and gather
        # from a dense embedding is the TPU lookup path.
        self._helper = LayerHelper("jit_beam_search_decoder", name=name)
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._beam_size = beam_size
        self._max_len = max_len
        self._end_id = end_id
        self._state_names = []      # registration order == engine order
        self._state_inits = {}
        self._state_phs = {}
        self._state_out_names = {}
        self._step_block = None
        self._outputs = None
        self._state_cell._enter_decoder(self)

    @property
    def state_cell(self):
        return self._state_cell

    def _make_backing(self, name, init_state):
        return _JitBacking(self, name, init_state)

    def _register_state(self, name, init, ph):
        self._state_names.append(name)
        self._state_inits[name] = init
        self._state_phs[name] = ph

    def _commit_state(self, name, new_value):
        self._state_out_names[name] = new_value.name

    def decode(self):
        if self._outputs is not None:
            raise ValueError("decode() already ran for this decoder")
        try:
            return self._decode()
        except Exception:
            # detach so the cell can be reused by another decoder after a
            # failed build (mirrors BeamSearchDecoder.block's unwind)
            if self._state_cell._decoder is self:
                self._state_cell._leave_decoder(self)
            raise

    def _decode(self):
        cell = self._state_cell
        program = self._helper.main_program
        parent_block = program.current_block()
        self._step_block = program._create_block()  # now current
        try:
            id_feed = self._step_block.create_var(
                name=unique_name.generate("jbs_prev_ids"),
                dtype="int64", shape=(-1, 1))
            prev_emb = layers.embedding(
                id_feed, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=False)

            feeds = {}
            ctx_phs, ctx_vars = [], []
            for name, var in self._input_var_dict.items():
                if name not in cell._inputs:
                    raise ValueError(
                        f"input_var_dict key {name!r} unknown to the cell")
                ph = self._step_block.create_var(
                    name=unique_name.generate(f"jbs_ctx_{name}"),
                    dtype=var.dtype,
                    shape=(-1,) + tuple((var.shape or ())[1:]))
                ctx_phs.append(ph.name)
                ctx_vars.append(var.name)
                feeds[name] = ph
            for name in cell._inputs:
                if name not in feeds:
                    feeds[name] = prev_emb

            cell.compute_state(inputs=feeds)
            cell.update_states()
            probs = layers.fc(input=cell.out_state(),
                              size=self._target_dict_dim, act="softmax")
        finally:
            program._rollback()

        # loop-invariant values the step reads but does not define:
        # parameters and any batch-independent captures
        defined = {id_feed.name} | set(self._state_phs[n].name
                                       for n in self._state_names)
        defined |= set(ctx_phs)
        written, x_names = set(), []
        for op in self._step_block.ops:
            for n in op.input_arg_names:
                if n and n not in written and n not in defined \
                        and n not in x_names \
                        and parent_block._has_var_recursive(n):
                    x_names.append(n)
            written.update(n for n in op.output_arg_names if n)

        def _out(name, dtype, shape):
            v = parent_block.create_var(
                name=unique_name.generate(name), dtype=dtype, shape=shape)
            v.stop_gradient = True
            return v

        L = self._max_len
        h_ids = _out("jbs_hist_ids", "int64", (L + 1, -1, self._beam_size))
        h_par = _out("jbs_hist_par", "int32", (L + 1, -1, self._beam_size))
        h_sc = _out("jbs_hist_sc", "float32",
                    (L + 1, -1, self._beam_size))
        n_steps = _out("jbs_nsteps", "int32", (1,))
        parent_block.append_op(
            type="jit_beam_search",
            inputs={"InitIds": [self._init_ids.name],
                    "InitScores": [self._init_scores.name],
                    "StateInit": [self._state_inits[n].name
                                  for n in self._state_names],
                    "Context": ctx_vars,
                    "X": x_names},
            outputs={"HistIds": [h_ids.name],
                     "HistParents": [h_par.name],
                     "HistScores": [h_sc.name],
                     "NumSteps": [n_steps.name]},
            attrs={"sub_block": self._step_block.idx,
                   "id_feed": id_feed.name,
                   "state_feeds": [self._state_phs[n].name
                                   for n in self._state_names],
                   "state_outs": [self._state_out_names[n]
                                  for n in self._state_names],
                   "ctx_feeds": ctx_phs,
                   "prob_var": probs.name,
                   "beam_size": int(self._beam_size),
                   "max_len": int(self._max_len),
                   "end_id": int(self._end_id),
                   "vocab_size": int(self._target_dict_dim)})

        out_ids = parent_block.create_var(
            name=unique_name.generate("jbs_sentence_ids"), dtype="int64",
            shape=(-1, 1), lod_level=2)
        out_scores = parent_block.create_var(
            name=unique_name.generate("jbs_sentence_scores"),
            dtype="float32", shape=(-1, 1), lod_level=2)
        out_ids.stop_gradient = out_scores.stop_gradient = True
        parent_block.append_op(
            type="beam_search_pack",
            inputs={"HistIds": [h_ids.name], "HistParents": [h_par.name],
                    "HistScores": [h_sc.name], "NumSteps": [n_steps.name]},
            outputs={"SentenceIds": [out_ids.name],
                     "SentenceScores": [out_scores.name]},
            attrs={"end_id": int(self._end_id)})
        self._outputs = (out_ids, out_scores)
        self._state_cell._leave_decoder(self)
        return self._outputs

    def __call__(self):
        if self._outputs is None:
            raise ValueError("run decode() before calling the decoder")
        return self._outputs
