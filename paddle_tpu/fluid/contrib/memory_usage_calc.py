"""DEPRECATED shim: estimate a Program's training memory footprint.

The hand-rolled sum-every-var heuristic this module shipped (ref:
python/paddle/fluid/contrib/memory_usage_calc.py) is retired — it priced
every intermediate at full size forever, with no liveness, no donation
and no sharding awareness.  :func:`memory_usage` keeps its public
signature but now delegates to the real pre-flight estimator,
``paddle_tpu.analysis.memcheck.estimate_program_memory`` (the AN5xx
verifier pass: persistent state + activation high-water over the block,
donation-aware), and brackets that estimate the same ±30% the reference
did.  New code should call the estimator directly — or read the
``memory.peak_bytes`` compiled-truth gauge (``paddle_tpu.observe.memory``)
after lowering — instead of this band.

The legacy math survives as :func:`_legacy_memory_usage` purely so the
regression suite can prove the delegation is same-or-better against the
compiled truth.
"""

from __future__ import annotations

import warnings

import numpy as np

from ..framework import Program, default_main_program
from .. import core

DTYPE_TO_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8, "bool": 1,
}


def _legacy_memory_usage(program: Program, batch_size: int):
    """The retired heuristic: sum EVERY var at full size (no liveness),
    ±30% band.  Kept only as the regression baseline."""
    total = 0.0
    for var in program.list_vars():
        shape = var.shape
        if shape is None:
            continue
        dims = [batch_size if (s is None or int(s) < 0) else int(s)
                for s in shape]
        try:
            item = DTYPE_TO_SIZE[core.convert_dtype(var.dtype)]
        except (KeyError, ValueError):
            continue
        total += float(np.prod(dims)) * item if dims else item
    mb = total / (1024.0 ** 2)
    return mb * 0.7, mb * 1.3


def memory_usage(program: Program = None, batch_size: int = 1):
    """Returns (low_MB, high_MB) for one training step at batch_size.

    DEPRECATED: delegates to the AN5xx pre-flight estimator
    (``paddle_tpu.analysis.memcheck``); prefer calling that directly, or
    reading the compiled ``memory.peak_bytes`` gauge."""
    warnings.warn(
        "fluid.contrib.memory_usage_calc.memory_usage is deprecated; use "
        "paddle_tpu.analysis.memcheck.estimate_program_memory (pre-flight)"
        " or the memory.peak_bytes gauge (compiled truth) instead",
        DeprecationWarning, stacklevel=2)
    program = program or default_main_program()
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    try:
        from ...analysis import _feed_infos
        from ...analysis.infer import run_infer_pass
        from ...analysis.memcheck import estimate_program_memory

        feed_infos, _ = _feed_infos(program, None, batch_size)
        env = run_infer_pass(program, 0, feed_infos, [], batch_size)
        est = estimate_program_memory(program, env, {}, feed_infos, [],
                                      batch_hint=batch_size)
    except Exception:
        est = None
    if est is None or est.get("peak_bytes", 0) <= 0:
        return _legacy_memory_usage(program, batch_size)
    mb = est["peak_bytes"] / (1024.0 ** 2)
    # keep the reference's ±30% bracket around the (much tighter) center
    return mb * 0.7, mb * 1.3
