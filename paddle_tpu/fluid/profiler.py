"""Profiler facade (ref: python/paddle/fluid/profiler.py:39-221).

The reference aggregates host events + CUPTI records; here the same API
fronts ``jax.profiler`` — traces open in TensorBoard/perfetto/XProf, which
is the TPU-native replacement for tools/timeline.py's Chrome trace.
"""

from __future__ import annotations

import contextlib
import os
import tempfile

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler"]

_trace_dir = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # no CUDA on this stack; kept as a no-op shim for API parity
    yield


def reset_profiler():
    pass


def start_profiler(state="All", trace_dir=None):
    global _trace_dir
    import jax

    _trace_dir = trace_dir or os.path.join(tempfile.gettempdir(),
                                           "paddle_tpu_profile")
    jax.profiler.start_trace(_trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    import jax

    jax.profiler.stop_trace()
    return _trace_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
