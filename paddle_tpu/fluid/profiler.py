"""Profiler: host event aggregation + jax trace (ref:
python/paddle/fluid/profiler.py:39-221 and platform/profiler.cc — the
reference aggregates push/pop host events into sorted tables and captures
device activity via CUPTI; here host events come from the executor's
block/segment/op timers and device activity from ``jax.profiler``, whose
traces open in TensorBoard/perfetto/XProf).

``stop_profiler`` prints the reference-style aggregate table (calls, total,
min, max, ave) and writes a JSON event log that ``tools/timeline.py``
converts to a chrome://tracing file (ref: tools/timeline.py:36,115).

Storage note (ISSUE 5): the counters and the [calls,total,min,max] event
aggregates used to live in module-level plain dicts — an unlocked
read-modify-write per update that DROPPED increments whenever serving
workers, the guardian observer and the training loop emitted concurrently.
Both now route through ``paddle_tpu.observe``'s process registry: counters
via ``registry.inc``/``set_gauge``, event aggregates via
``registry.record_timing``, and this module's timeline list is mutated
under the registry's own lock, so one lock covers all profiler state.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler", "record_event", "is_profiling",
           "record_counter", "counters",
           "device_op_table", "lower_program_hlo"]

_trace_dir = None
_on = False
_timeline = []   # {"name", "ts", "dur"} microseconds since start
_t0 = 0.0


def _registry():
    from .. import observe

    return observe.registry()


def is_profiling() -> bool:
    return _on


def record_event(name: str, seconds: float, start: float = None) -> None:
    """Aggregate one timed host event (executor hooks call this)."""
    if not _on:
        return
    from ..observe import trace as _trace

    reg = _registry()
    reg.record_timing(name, seconds)
    ts = ((start if start is not None else time.perf_counter() - seconds)
          - _t0) * 1e6
    # stamp the emitting thread so tools/timeline.py renders concurrent
    # events (prefetch staging vs executor dispatch) on separate rows
    tid = _trace.thread_tid()
    with reg.lock:
        _timeline.append({"name": name, "ts": ts, "dur": seconds * 1e6,
                          "tid": tid})


def record_counter(name: str, inc: int = 1, value=None) -> None:
    """ServingMetrics-style counter/gauge, ALWAYS on (unlike record_event
    it does not require an active profiling session — production counters
    must not depend on tracing being enabled).  Default increments by
    ``inc``; ``value=`` sets a gauge absolutely (e.g. the guardian's
    current loss scale).  Thread-safe: backed by the observe registry's
    lock, so concurrent emitters never lose increments."""
    if value is not None:
        _registry().set_gauge(name, value)
    else:
        _registry().inc(name, inc)


def counters() -> dict:
    """Snapshot of all counters/gauges (guardian trips/skips/loss-scale,
    plus anything subsystems recorded) — the flat compatibility view of
    ``paddle_tpu.observe.registry()``."""
    return _registry().flat()


@contextlib.contextmanager
def _event(name):
    t = time.perf_counter()
    try:
        yield
    finally:
        record_event(name, time.perf_counter() - t, start=t)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # no CUDA on this stack; kept as a no-op shim for API parity
    yield


def reset_profiler():
    reg = _registry()
    with reg.lock:
        reg.clear(timings_only=True)
        _timeline.clear()


def start_profiler(state="All", trace_dir=None):
    global _trace_dir, _on, _t0
    import jax

    reset_profiler()
    _t0 = time.perf_counter()
    # per-change counter samples for the chrome-trace "C" track (queue
    # depth, cache hits... over time); recorded only while profiling
    _registry().start_sampling(_t0)
    _on = True
    _trace_dir = trace_dir or os.path.join(tempfile.gettempdir(),
                                           "paddle_tpu_profile")
    try:
        jax.profiler.start_trace(_trace_dir)
    except RuntimeError:
        pass  # a trace may already be active


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop tracing, print the aggregate table, write the event log.

    sorted_key in {None, 'calls', 'total', 'max', 'min', 'ave'} mirrors the
    reference's EnableProfiler table ordering (platform/profiler.h:116)."""
    global _on
    import jax

    _on = False
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass

    reg = _registry()
    samples = reg.stop_sampling()
    rows = [(n, c, tot, mn, mx, tot / c)
            for n, (c, tot, mn, mx) in reg.timings().items()]
    key_idx = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}
    rows.sort(key=lambda r: -r[key_idx.get(sorted_key, 2)])
    if rows:
        print(f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} "
              f"{'Min(ms)':>10} {'Max(ms)':>10} {'Ave(ms)':>10}")
        for n, c, tot, mn, mx, ave in rows:
            print(f"{n[:40]:<40} {c:>8} {tot * 1e3:>12.3f} "
                  f"{mn * 1e3:>10.3f} {mx * 1e3:>10.3f} {ave * 1e3:>10.3f}")
    if profile_path:
        from ..observe.events import host_name

        with reg.lock:
            events = list(_timeline)
        with open(profile_path, "w") as f:
            # "host" + "counters" feed tools/timeline.py's multi-host merge
            # (distinct pids) and its "ph":"C" counter tracks
            json.dump({"events": events, "trace_dir": _trace_dir,
                       "host": host_name(), "counters": samples}, f)
    return _trace_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# Per-op DEVICE timeline (VERDICT r4 missing #5).
#
# ref: platform/device_tracer.h:49 — the reference correlates CUPTI device
# records back to framework ops via correlation ids.  The XLA-native
# equivalent: Executor.run_op wraps every op's trace in
# jax.named_scope(op.type), so the compiler stamps each HLO instruction's
# metadata op_name with "jit(..)/<op_type>/<primitive>"; the profiler's
# xplane capture then carries per-HLO-instruction device durations, and
# joining the two attributes measured device time to framework op types —
# with the honest caveat that XLA FUSES across ops, so a fusion's time is
# attributed to the op named in its root instruction's metadata.
# ---------------------------------------------------------------------------


def _parse_hlo_op_names(hlo_text: str):
    """instruction name -> framework op type, from metadata op_name scopes.

    HLO: `%fusion.3 = ... metadata={op_name="jit(fn)/conv2d/conv_general..`
    The first scope segment after the jit(...) prefix is the named_scope
    the executor pushed, i.e. the fluid op type."""
    import re

    mapping = {}
    for m in re.finditer(
            r"%?([\w.\-]+)\s*=\s*[^\n]*?metadata=\{[^}]*?"
            r"op_name=\"([^\"]+)\"", hlo_text):
        inst, op_name = m.group(1), m.group(2)
        parts = op_name.split("/")
        if parts and parts[0].startswith("jit("):
            parts = parts[1:]
        if parts:
            mapping[inst] = parts[0]
    return mapping


def device_op_table(trace_dir=None, hlo_text=None, print_table=True):
    """Aggregate per-HLO-op DEVICE time from the newest xplane capture.

    Returns rows sorted by total time:
      {"hlo_op", "calls", "total_us", "avg_us"[, "fluid_op"]}
    ``trace_dir`` defaults to the last start_profiler/stop_profiler dir.
    ``hlo_text`` (from ``lower_program_hlo``) adds the fluid_op column by
    joining instruction names against HLO metadata op_name scopes."""
    import glob

    d = trace_dir or _trace_dir
    if not d:
        raise ValueError("no trace_dir: run under profiler()/start_profiler "
                         "or pass trace_dir")
    pbs = sorted(glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                           recursive=True), key=os.path.getmtime)
    if not pbs:
        raise IOError(f"no .xplane.pb under {d}")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as exc:  # pragma: no cover - env without tensorflow
        raise ImportError(
            "device_op_table needs the xplane proto (tensorflow.tsl); "
            "open the trace in TensorBoard/XProf instead") from exc

    xs = xplane_pb2.XSpace()
    with open(pbs[-1], "rb") as f:
        xs.ParseFromString(f.read())
    agg = {}
    for plane in xs.planes:
        smeta = {k: v.name for k, v in plane.stat_metadata.items()}
        emeta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            for ev in line.events:
                stat_names = {smeta.get(s.metadata_id, "") for s in ev.stats}
                # device-executed HLO instructions carry an hlo_op stat;
                # whole-module events (the "XLA Modules" line) carry only
                # hlo_module and would double-count every op under them
                if "hlo_op" not in stat_names:
                    continue
                name = emeta.get(ev.metadata_id, "?")
                e = agg.setdefault(name, [0, 0.0])
                e[0] += 1
                e[1] += ev.duration_ps / 1e6  # ps -> us
    name_map = _parse_hlo_op_names(hlo_text) if hlo_text else {}
    rows = []
    for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        row = {"hlo_op": name, "calls": calls,
               "total_us": round(total, 1),
               "avg_us": round(total / calls, 2)}
        if name_map:
            row["fluid_op"] = name_map.get(name, "")
        rows.append(row)
    if print_table and rows:
        cols = f"{'HLO op':<44} {'Calls':>6} {'Total(us)':>12} {'Avg(us)':>10}"
        if name_map:
            cols += f" {'Fluid op':<18}"
        print(cols)
        for r in rows:
            line_ = (f"{r['hlo_op'][:44]:<44} {r['calls']:>6} "
                     f"{r['total_us']:>12.1f} {r['avg_us']:>10.2f}")
            if name_map:
                line_ += f" {r.get('fluid_op', ''):<18}"
            print(line_)
    return rows


def lower_program_hlo(program, feed, fetch_list, scope=None,
                      optimized=True, feed_lods=None):
    """Compile a Program's block the way the Executor would and return the
    (optimized) HLO text — instruction metadata carries the per-op
    named_scope labels, so this is the join key for device_op_table.

    ``feed`` maps name -> ndarray (concrete shapes pick the specialization);
    ``feed_lods`` maps name -> offsets-form LoD for sequence feeds (state
    LoDs recorded by earlier runs come from the scope, as in
    Executor.run); ``optimized=False`` returns the pre-optimization
    stable-HLO lowering."""
    import jax

    from .executor import LOD_SUFFIX, BlockPlan, global_scope, trace_block
    from .framework import RNG_STATE_VAR, Variable

    scope = scope or global_scope()
    fetch_names = [f.name if isinstance(f, Variable) else str(f)
                   for f in fetch_list]
    plan = BlockPlan(program, 0, list(feed), fetch_names)
    state = {n: scope.get(n) for n in plan.state_in}
    if plan.needs_rng:
        import jax.random as jrandom

        state[RNG_STATE_VAR] = jrandom.PRNGKey(program.random_seed or 0)
    # sequence programs read '<name>@LOD' static metadata; mirror
    # Executor.run's state_lods + feed_lods env (executor.py:624)
    all_lods = {n: lod for n, lod in getattr(scope, "_lods", {}).items()
                if lod and program.global_block()._has_var_recursive(n)}
    all_lods.update(feed_lods or {})
    static_env = {k + LOD_SUFFIX: tuple(tuple(level) for level in lod)
                  for k, lod in all_lods.items()}

    def fn(feed_vals, state_vals):
        return trace_block(program, 0, plan, feed_vals, state_vals,
                           static_env=static_env)

    lowered = jax.jit(fn).lower(feed, state)
    if not optimized:
        return lowered.as_text()
    return lowered.compile().as_text()
