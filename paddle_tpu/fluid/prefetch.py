"""Double-buffered host→device input prefetch for windowed training.

The fused window (``Executor.run_steps``) removes per-step dispatch
latency, which leaves input staging as the serial tail: a ``feed_per_step``
training loop reads window k's batches, stacks them to ``(n_steps, ...)``
arrays and ships them host→device *between* dispatches, so the device
idles while the host does IO.  :class:`DevicePrefetcher` moves that work
onto a background thread with a bounded queue of device-resident windows —
while the device runs window k, the host is already reading and
``device_put``-ing window k+1 (the host-side analogue of the reference's
in-graph reader loop, ref benchmark/fluid/fluid_benchmark.py:149, where
the data pipeline runs concurrently with compute by construction).

Contract (matches the reader decorators' PR-3 hardening):

 - bounded depth: at most ``depth`` staged windows are ever alive
   (``PADDLE_TPU_PREFETCH_DEPTH``, default 2 — double buffering); device
   memory use is bounded at ``depth x window_bytes``;
 - worker exceptions propagate to the consumer instead of silently
   killing the thread (which would deadlock the consumer's queue get);
 - clean shutdown: an early-exiting consumer (``stop()``/break) flips an
   abort event and the worker drains via timeout-puts, never wedging on a
   queue nobody reads;
 - ``depth=0`` stages synchronously in the caller's thread — the
   baseline the overlap oracle (tests/test_prefetch.py) compares against.

``fluid.fault.io_delay()`` is consulted once per staged window, so
``PADDLE_FAULT_IO_DELAY_MS`` deterministically models slow input IO: the
synchronous path pays it inline, the prefetched path overlaps it with the
device's current window.
"""

from __future__ import annotations

import os
from collections import deque
from queue import Empty, Full, Queue
from threading import Event, Thread
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

__all__ = ["DevicePrefetcher", "default_depth", "iter_device_samples"]

_END = object()


class _WorkerError:
    """Exception captured on the staging thread, queued so the CONSUMER
    re-raises it (same contract as reader.decorator's buffered/xmap)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def default_depth() -> int:
    """The env-configured prefetch depth (``PADDLE_TPU_PREFETCH_DEPTH``,
    default 2: double buffering — one window on device, one staging)."""
    from . import envcontract

    try:
        return max(0, int(envcontract.get("PADDLE_TPU_PREFETCH_DEPTH")))
    except ValueError:
        return 2


def _resolve_device(place):
    import jax

    if place is not None:
        from . import core

        return core.get_jax_device(place)
    return jax.devices()[0]


def _background_iter(src_iter, stage_fn, depth: int, abort: Event):
    """Yield ``stage_fn(item)`` for every item of ``src_iter``, with the
    staging running on a background thread ``depth`` items ahead."""
    q: Queue = Queue(maxsize=max(1, depth))

    def _put(item) -> bool:
        while not abort.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except Full:
                continue
        return False

    def work():
        try:
            for item in src_iter:
                if abort.is_set():
                    return
                if not _put(stage_fn(item)):
                    return
        except BaseException as exc:
            _put(_WorkerError(exc))
            return
        _put(_END)

    t = Thread(target=work, name="device-prefetch", daemon=True)
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=0.05)
            except Empty:
                if not t.is_alive() and q.empty():
                    # worker died without posting (only possible if abort
                    # raced its final put) — nothing more is coming
                    return
                continue
            if item is _END:
                return
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item
    finally:
        abort.set()


def _windows(source, n_steps: int):
    batches = []
    for sample in source:
        batches.append(sample)
        if len(batches) == n_steps:
            yield batches
            batches = []
    if batches:
        yield batches  # tail window (count < n_steps)


class DevicePrefetcher:
    """Iterate ``(feed_dev, count)`` windows staged on the device.

    ``source`` is an iterable of per-step feed dicts (``{name: array}``,
    what ``DataFeeder.feed`` returns); every ``n_steps`` consecutive dicts
    are stacked to a leading window dim and ``device_put`` — ready to pass
    straight to ``Executor.run_steps(feed=feed_dev, n_steps=count,
    feed_per_step=True)``.  The final window may be short (``count <
    n_steps``); the caller dispatches it with its actual count.
    """

    def __init__(self, source: Iterable[Dict[str, object]], n_steps: int = 1,
                 place=None, depth: Optional[int] = None, stage_fn=None):
        self.n_steps = max(1, int(n_steps))
        self.depth = default_depth() if depth is None else max(0, int(depth))
        self._source = source
        self._place = place
        self._device = None
        self._abort = Event()
        # stage_fn({name: stacked (count, batch, ...) array}) -> placed
        # dict: overrides the single-device device_put — the sharded
        # training path passes ParallelExecutor.stage_window so windows
        # land on the mesh with the batch axis already dp-sharded
        self._stage_fn = stage_fn
        # span linkage (observe.trace): the worker thread emits one
        # "prefetch.stage" span per staged window and queues its id here
        # (FIFO, mirrors the item queue); the consumer pops it into
        # ``last_stage_span`` as it takes each window, so the consuming
        # window's span can carry a ``staged_span`` link even though the
        # two live on different threads
        self._stage_spans: deque = deque()
        self._parent_span = None
        self.last_stage_span: Optional[str] = None
        # live-buffer ledger (observe.memory): bytes of each staged-but-
        # unconsumed window, FIFO next to the stage spans — staging adds
        # to the "prefetch" scope, consumption hands the bytes off
        self._staged_bytes: deque = deque()

    # -- staging --
    def _stage(self, batches) -> Tuple[Dict[str, object], int]:
        from . import fault as _fault
        from ..observe import trace as _trace

        sp = _trace.start_span("prefetch.stage", parent=self._parent_span,
                               count=len(batches))
        _fault.io_delay()  # deterministic slow-input oracle (module doc)
        import jax

        window = {name: np.stack([np.asarray(b[name]) for b in batches])
                  for name in batches[0]}
        if self._stage_fn is not None:
            placed = self._stage_fn(window)
        else:
            if self._device is None:
                self._device = _resolve_device(self._place)
            placed = {name: jax.device_put(arr, self._device)
                      for name, arr in window.items()}
        if sp is not None:
            sp.end()
            self._stage_spans.append(sp.span_id)
        else:
            self._stage_spans.append(None)
        from ..observe import memory as _obsmem

        nbytes = sum(int(getattr(v, "nbytes", 0) or 0)
                     for v in placed.values())
        self._staged_bytes.append(nbytes)
        _obsmem.adjust_staged(nbytes)
        return placed, len(batches)

    def __iter__(self):
        from ..observe import trace as _trace

        # staging spans parent to whatever was open when iteration began
        # (the trainer's epoch span, usually) — NOT to per-window spans,
        # which come and go while the worker runs ahead
        self._parent_span = _trace.current()
        wins = _windows(self._source, self.n_steps)
        if self.depth == 0:
            # synchronous mode: stage in the caller's thread, on demand
            for batches in wins:
                if self._abort.is_set():
                    return
                item = self._stage(batches)
                self.last_stage_span = (self._stage_spans.popleft()
                                        if self._stage_spans else None)
                self._consume_staged()
                yield item
            return
        for item in _background_iter(wins, self._stage, self.depth,
                                     self._abort):
            self.last_stage_span = (self._stage_spans.popleft()
                                    if self._stage_spans else None)
            self._consume_staged()
            yield item

    def _consume_staged(self) -> None:
        """Hand the oldest staged window's bytes off the prefetch scope
        (ownership moved to the consumer's dispatch)."""
        if self._staged_bytes:
            from ..observe import memory as _obsmem

            _obsmem.adjust_staged(-self._staged_bytes.popleft())

    def close(self) -> None:
        """Stop the staging thread; safe to call repeatedly."""
        self._abort.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def iter_device_samples(reader, depth: Optional[int] = None, place=None):
    """Sample-level device staging for the reader-decorator surface
    (:func:`paddle_tpu.reader.decorator.device_buffered`): yield the
    reader's samples with every array element already ``device_put``, the
    transfers issued ``depth`` samples ahead on a background thread."""
    import jax

    device = _resolve_device(place)
    depth = default_depth() if depth is None else max(1, int(depth))

    def stage(sample):
        def put(x):
            return (jax.device_put(x, device)
                    if isinstance(x, np.ndarray) else x)

        if isinstance(sample, dict):
            return {k: put(v) for k, v in sample.items()}
        if isinstance(sample, (tuple, list)):
            return type(sample)(put(x) for x in sample)
        return put(sample)

    yield from _background_iter(iter(reader()), stage, depth, Event())
