"""paddle_tpu.fluid — the Fluid-compatible user API, executing on XLA.

ref: python/paddle/fluid/__init__.py.  ``fluid.TPUPlace()`` is the north-star
addition (BASELINE.json): Executor(TPUPlace()) traces Programs into XLA
computations on TPU HBM instead of dispatching CUDA kernels.
"""

# ops must register before any program executes
from .. import ops as _ops  # noqa: F401

from . import core
from .core import CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace
from . import amp
from . import framework
from .framework import (Program, Operator, Parameter, Variable,
                        default_main_program, default_startup_program,
                        program_guard, name_scope)
from . import executor
from .executor import Executor, Scope, global_scope, scope_guard
from . import backward
from .backward import append_backward, calc_gradient
from . import initializer
from . import layers
from . import nets
from . import optimizer
from . import regularizer
from . import clip
from . import metrics
from . import average
from . import profiler
from . import unique_name
from . import io
from .io import (save_vars, save_params, save_persistables, load_vars,
                 load_params, load_persistables, save_inference_model,
                 load_inference_model, get_inference_program)
from .param_attr import ParamAttr, WeightNormParamAttr
from .data_feeder import DataFeeder
from . import parallel_executor
from .parallel_executor import ParallelExecutor, ExecutionStrategy, BuildStrategy
from . import transpiler
from .transpiler import DistributeTranspiler, InferenceTranspiler, memory_optimize, release_memory

from . import lod_tensor
from .lod_tensor import (LoDTensor, create_lod_tensor,
                         create_random_int_lodtensor)
from . import trainer
from .trainer import (Trainer, CheckpointConfig, BeginEpochEvent,
                      EndEpochEvent, BeginStepEvent, EndStepEvent,
                      Inferencer)
from . import fault
from . import guardian
from .guardian import NumericsTripped
from . import prefetch
from .prefetch import DevicePrefetcher
from . import evaluator
from . import debugger
from . import ir
from . import contrib

Tensor = framework.Variable

__all__ = [
    "io", "initializer", "layers", "nets", "optimizer", "backward", "amp",
    "fault", "guardian", "NumericsTripped", "prefetch", "DevicePrefetcher",
    "regularizer", "metrics", "clip", "profiler", "unique_name",
    "Program", "Operator", "Parameter", "Variable",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "Executor", "Scope", "global_scope", "scope_guard",
    "append_backward", "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "TPUPlace",
    "ParamAttr", "WeightNormParamAttr", "DataFeeder", "ParallelExecutor",
    "ExecutionStrategy", "BuildStrategy", "DistributeTranspiler",
    "InferenceTranspiler", "memory_optimize", "release_memory",
    "LoDTensor", "create_lod_tensor", "create_random_int_lodtensor",
    "Trainer", "CheckpointConfig", "BeginEpochEvent", "EndEpochEvent",
    "BeginStepEvent", "EndStepEvent", "Inferencer",
]
