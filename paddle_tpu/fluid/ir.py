"""IR graph & pass infrastructure (ref: paddle/fluid/framework/ir/ —
Graph/Node :graph.h:63/node.h:27, Pass registry :pass.h:32,
GraphPatternDetector powering the fusion passes, graph_to_program_pass).

Role on TPU: XLA already does kernel fusion, so the *performance* passes of
the reference (fc_fuse, conv_relu, …) are unnecessary; what remains
valuable is program-REWRITE infrastructure — inference folds (conv+BN),
dead-op elimination, custom user rewrites — expressed over a dataflow view
of a Program and serialized back (graph_to_program).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

import numpy as np

from .framework import Program

__all__ = ["Node", "Graph", "Pass", "PassRegistry", "register_pass",
           "get_pass", "apply_pass"]


class Node:
    """Op node or var node (ref node.h:27: a node is exactly one of the
    two; edges are def-use)."""

    def __init__(self, kind, name, op=None, var=None):
        self.kind = kind          # "op" | "var"
        self.name = name
        self.op = op              # framework.Operator for op nodes
        self.var = var            # framework.Variable for var nodes
        self.inputs: List[Node] = []
        self.outputs: List[Node] = []

    def is_op(self, type=None):
        return self.kind == "op" and (type is None or self.op.type == type)

    def is_var(self):
        return self.kind == "var"

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"Node({self.kind}:{self.name})"


class Graph:
    """Dataflow view over ONE block of a Program (ref graph.h:63 builds the
    same structure from a ProgramDesc).  Mutations happen on the node set;
    ``to_program`` writes the surviving/modified op list back in a valid
    topological order (graph_to_program_pass)."""

    def __init__(self, program: Program, block_idx: int = 0):
        self.program = program
        self.block_idx = block_idx
        block = program.block(block_idx)
        self.op_nodes: List[Node] = []
        self.var_nodes: Dict[str, Node] = {}
        for op in block.ops:
            self._add_op(op, block)

    def _var_node(self, name, block):
        if name not in self.var_nodes:
            var = block._var_recursive(name) \
                if block._has_var_recursive(name) else None
            self.var_nodes[name] = Node("var", name, var=var)
        return self.var_nodes[name]

    def _add_op(self, op, block):
        node = Node("op", op.type, op=op)
        for name in op.input_arg_names:
            if not name:
                continue
            vn = self._var_node(name, block)
            node.inputs.append(vn)
            vn.outputs.append(node)
        for name in op.output_arg_names:
            if not name:
                continue
            vn = self._var_node(name, block)
            node.outputs.append(vn)
            vn.inputs.append(node)
        self.op_nodes.append(node)
        return node

    # -- queries --
    def ops(self, type: Optional[str] = None) -> List[Node]:
        return [n for n in self.op_nodes
                if type is None or n.op.type == type]

    def var(self, name: str) -> Optional[Node]:
        return self.var_nodes.get(name)

    def sole_consumer(self, var_node: Node) -> Optional[Node]:
        """The single op reading this var, or None (pattern-matching
        helper, the PDNode 'single out-link' constraint)."""
        return var_node.outputs[0] if len(var_node.outputs) == 1 else None

    # -- mutations --
    def remove_op(self, node: Node):
        self.op_nodes.remove(node)
        for vn in node.inputs:
            vn.outputs = [o for o in vn.outputs if o is not node]
        for vn in node.outputs:
            vn.inputs = [i for i in vn.inputs if i is not node]

    def to_program(self) -> Program:
        """Write the surviving op list back into the block (ops keep their
        relative order, which the Graph preserves — ref
        graph_to_program_pass.cc)."""
        block = self.program.block(self.block_idx)
        block.ops = [n.op for n in self.op_nodes]
        self.program._bump_version()
        return self.program


class Pass:
    """Subclass and implement apply(graph) -> graph (ref pass.h:32)."""

    name = "pass"

    def apply(self, graph: Graph) -> Graph:
        raise NotImplementedError

    def __call__(self, program: Program, block_idx: int = 0) -> Program:
        return self.apply(Graph(program, block_idx)).to_program()


class PassRegistry:
    _passes: Dict[str, Callable[[], Pass]] = {}

    @classmethod
    def register(cls, name, factory):
        cls._passes[name] = factory

    @classmethod
    def get(cls, name, **kwargs) -> Pass:
        if name not in cls._passes:
            raise KeyError(f"no pass named {name!r}; have "
                           f"{sorted(cls._passes)}")
        return cls._passes[name](**kwargs)


def register_pass(name):
    def deco(klass):
        klass.name = name
        PassRegistry.register(name, klass)
        return klass

    return deco


def get_pass(name, **kwargs) -> Pass:
    return PassRegistry.get(name, **kwargs)


def apply_pass(program: Program, name: str, block_idx: int = 0,
               **kwargs) -> Program:
    return get_pass(name, **kwargs)(program, block_idx)


# ---------------------------------------------------------------------------
# Built-in passes
# ---------------------------------------------------------------------------


@register_pass("dead_op_elimination")
class DeadOpElimination(Pass):
    """Drop ops none of whose outputs are read, target, persistable, or
    side-effecting — the graph-level twin of the executor's live-op slice
    (ref: framework/prune.cc for the desc-level version).  ``targets``
    names the program outputs the caller intends to fetch."""

    SIDE_EFFECTS = {"print", "save", "save_combine", "feed", "fetch"}

    def __init__(self, targets=()):
        self.targets: Set[str] = {
            t if isinstance(t, str) else t.name for t in targets}
        if not self.targets:
            # fetch targets live OUTSIDE the program in this executor model
            # (BlockPlan fetch_names, no fetch ops) — an empty target set
            # would cascade-delete the whole forward graph
            raise ValueError(
                "dead_op_elimination requires explicit targets (the vars "
                "you intend to fetch); ref prune.cc takes targets too")

    def _subblock_live(self, program, op) -> bool:
        """True when a control-flow op's sub-block (recursively) contains
        a side-effecting op or writes persistable/checkpoint-visible
        state — invisible to outer-block def-use liveness, so such ops
        must never be eliminated on output-deadness alone."""
        sub = op.attr("sub_block") if hasattr(op, "attr") else None
        if not isinstance(sub, int) or sub >= len(program.blocks):
            return False
        block = program.block(sub)
        for bop in block.ops:
            if bop.type in self.SIDE_EFFECTS:
                return True
            for n in bop.output_arg_names:
                if n and block._has_var_recursive(n) \
                        and block._var_recursive(n).persistable:
                    return True
            if self._subblock_live(program, bop):
                return True
        return False

    def apply(self, graph: Graph) -> Graph:
        changed = True
        while changed:
            changed = False
            for node in list(graph.op_nodes):
                if node.op.type in self.SIDE_EFFECTS:
                    continue
                if self._subblock_live(graph.program, node.op):
                    continue
                live = False
                for vn in node.outputs:
                    if vn.outputs or vn.name in self.targets:
                        live = True
                        break
                    if vn.var is not None and vn.var.persistable:
                        live = True
                        break
                if not live:
                    graph.remove_op(node)
                    changed = True
        return graph


@register_pass("conv_bn_fuse")
class ConvBNFuse(Pass):
    """Fold an inference-mode batch_norm into the preceding conv2d's
    weights (ref: the InferenceTranspiler's BN fold and
    conv_bn_fuse_pass): W' = W * gamma/std per out-channel, and the op pair
    collapses to conv2d + elementwise_add of a precomputed bias.

    Only legal when the BN is is_test=True and the conv output feeds ONLY
    the BN.  Works on the numeric values in the given scope, so it runs at
    inference-load time (like the reference transpiler, which edits both
    program and weights)."""

    def __init__(self, scope=None):
        from .executor import global_scope

        self.scope = scope or global_scope()

    def apply(self, graph: Graph) -> Graph:
        block = graph.program.block(graph.block_idx)
        folded_filters: Set[str] = set()
        for conv in list(graph.ops("conv2d")):
            out_vn = next((vn for vn in conv.outputs), None)
            if out_vn is None:
                continue
            bn = graph.sole_consumer(out_vn)
            if bn is None or not bn.is_op("batch_norm") \
                    or not bn.op.attr("is_test", False):
                continue
            names = {s: bn.op.inputs[s][0] for s in
                     ("Scale", "Bias", "Mean", "Variance")}
            w_name = conv.op.inputs["Filter"][0]
            w_vn = graph.var(w_name)
            shared = w_vn is not None and \
                sum(1 for c in w_vn.outputs if c.is_op("conv2d")) > 1
            if shared or w_name in folded_filters:
                # a filter consumed by several convs cannot absorb one BN's
                # statistics without corrupting the others — skip
                continue
            folded_filters.add(w_name)
            vals = {k: self.scope.get(n) for k, n in names.items()}
            w = self.scope.get(w_name)
            if w is None or any(v is None for v in vals.values()):
                continue
            eps = bn.op.attr("epsilon", 1e-5)
            gamma = np.asarray(vals["Scale"], np.float32)
            beta = np.asarray(vals["Bias"], np.float32)
            mean = np.asarray(vals["Mean"], np.float32)
            var = np.asarray(vals["Variance"], np.float32)
            std = np.sqrt(var + eps)
            w = np.asarray(w, np.float32) * (gamma / std)[:, None, None, None]
            bias = beta - gamma * mean / std
            self.scope.set(w_name, w)
            bias_name = w_name + "@bn_fold_bias"
            self.scope.set(bias_name, bias.astype(np.float32))
            block.create_var(name=bias_name, shape=tuple(bias.shape),
                             dtype="float32", persistable=True)
            # rewrite: conv_out -> add(conv_out, bias) replaces the BN
            bn_out = bn.op.outputs["Y"][0]
            from .framework import Operator

            add_op = Operator(
                block, "elementwise_add",
                inputs={"X": [out_vn.name], "Y": [bias_name]},
                outputs={"Out": [bn_out]}, attrs={"axis": 1})
            idx = graph.op_nodes.index(bn)
            graph.remove_op(bn)
            new_node = Node("op", "elementwise_add", op=add_op)
            bias_vn = graph._var_node(bias_name, block)
            new_node.inputs = [out_vn, bias_vn]
            out_vn.outputs.append(new_node)
            bias_vn.outputs.append(new_node)  # keep def-use symmetric
            bn_out_vn = graph._var_node(bn_out, block)
            new_node.outputs = [bn_out_vn]
            bn_out_vn.inputs = [new_node]
            graph.op_nodes.insert(idx, new_node)
        return graph
