"""NN layers (ref: python/paddle/fluid/layers/nn.py — ~110 layers).

Layers build IR ops; they do best-effort static shape propagation (batch dims
stay -1) so downstream layers can size their parameters, mirroring the
reference's compile-time InferShape.
"""

from __future__ import annotations

import copy

import numpy as np

from .. import core
from ..framework import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "pool2d",
    "batch_norm", "layer_norm", "group_norm", "dropout", "softmax",
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "mean", "mul",
    "matmul", "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min", "elementwise_pow",
    "scale", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "reshape", "transpose", "split", "topk", "one_hot", "lrn",
    "l2_normalize", "clip", "clip_by_norm", "label_smooth", "smooth_l1",
    "gather", "scatter", "pad", "pad2d", "pad_constant_like", "squeeze",
    "unsqueeze", "stack", "unstack", "expand", "slice", "shape", "flatten",
    "im2sequence", "maxout", "relu", "log", "crop", "mean_iou",
    "image_resize", "resize_bilinear", "autoincreased_step_counter",
    "lod_reset", "prelu", "dice_loss", "log_loss", "huber_loss",
    "ring_attention", "moe_ffn", "gpipe_mlp_stack",
    "kv_cache_update", "kv_cache_scatter", "token_select",
    "paged_attention", "spec_accept",
    "transformer_encoder_stack", "transformer_decoder_stack", "cos_sim",
    "multiplex", "pool3d", "random_crop", "rank_loss",
    "image_resize_short", "Print", "load",
    "linear_chain_crf", "crf_decoding", "nce", "hsigmoid", "warpctc",
    "edit_distance", "ctc_greedy_decoder", "sequence_erase",
]


def _dim_or(v, default=-1):
    return default if v is None else v


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """ref: layers/nn.py fc — emitted as mul(+sum)+elementwise_add+act, the
    same decomposition the reference uses; XLA fuses it back into one GEMM."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr_ in helper.iter_inputs_and_params():
        in_shape = input_var.shape
        param_shape = [int(np.prod(in_shape[num_flatten_dims:]))] + [size]
        w = helper.create_parameter(attr=param_attr_, shape=param_shape,
                                    dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        tmp.shape = tuple(in_shape[:num_flatten_dims]) + (size,)
        helper.append_op(
            type="mul", inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        pre_bias.shape = mul_results[0].shape
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    ids_shape = input.shape
    if ids_shape and ids_shape[-1] == 1:
        out.shape = tuple(ids_shape[:-1]) + (size[1],)
    else:
        out.shape = tuple(ids_shape or ()) + (size[1],)
    helper.append_op(
        type="lookup_table", inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": -1 if padding_idx is None else padding_idx})
    return out


def _conv_out_dim(size, k, pad, stride, dilation=1):
    if size in (-1, None):
        return -1
    return (size + 2 * pad - (dilation * (k - 1) + 1)) // stride + 1


def _to_list(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    filter_size = _to_list(filter_size, 2)
    stride = _to_list(stride, 2)
    padding = _to_list(padding, 2)
    dilation = _to_list(dilation, 2)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    def _std(shape):
        fan_in = num_channels * shape[2] * shape[3] // groups
        return (2.0 / fan_in) ** 0.5

    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, _std(filter_shape)))
    out = helper.create_variable_for_type_inference(dtype)
    n, c, h, wd = input.shape
    out.shape = (n, num_filters,
                 _conv_out_dim(h, filter_size[0], padding[0], stride[0], dilation[0]),
                 _conv_out_dim(wd, filter_size[1], padding[1], stride[1], dilation[1]))
    helper.append_op(
        type="conv2d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "use_cudnn": use_cudnn})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    filter_size = _to_list(filter_size, 3)
    stride = _to_list(stride, 3)
    padding = _to_list(padding, 3)
    dilation = _to_list(dilation, 3)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    dims = input.shape
    out.shape = (dims[0], num_filters) + tuple(
        _conv_out_dim(dims[2 + i], filter_size[i], padding[i], stride[i],
                      dilation[i]) for i in range(3))
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _to_list(stride, 2)
    padding = _to_list(padding, 2)
    dilation = _to_list(dilation, 2)
    if filter_size is None:
        if output_size is None:
            raise ValueError("need filter_size or output_size")
        output_size = _to_list(output_size, 2)
        h, w_ = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h - 1) * stride[0] + 2 * padding[0] - 1) //
            dilation[0] + 1,
            (output_size[1] - (w_ - 1) * stride[1] + 2 * padding[1] - 1) //
            dilation[1] + 1]
    else:
        filter_size = _to_list(filter_size, 2)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    n, c, h, wd = input.shape

    def _out_dim(size, k, pad, s, d):
        if size in (-1, None):
            return -1
        return (size - 1) * s - 2 * pad + d * (k - 1) + 1

    out.shape = (n, num_filters,
                 _out_dim(h, filter_size[0], padding[0], stride[0], dilation[0]),
                 _out_dim(wd, filter_size[1], padding[1], stride[1], dilation[1]))
    helper.append_op(
        type="conv2d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, name=None,
           exclusive=True):
    helper = LayerHelper("pool2d", **locals())
    pool_size = _to_list(pool_size, 2)
    pool_stride = _to_list(pool_stride, 2)
    pool_padding = _to_list(pool_padding, 2)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    n, c, h, w = input.shape
    if global_pooling:
        out.shape = (n, c, 1, 1)
    else:
        def _po(size, k, pad, s):
            if size in (-1, None):
                return -1
            if ceil_mode:
                return (size - k + 2 * pad + s - 1) // s + 1
            return (size - k + 2 * pad) // s + 1
        out.shape = (n, c, _po(h, pool_size[0], pool_padding[0], pool_stride[0]),
                     _po(w, pool_size[1], pool_padding[1], pool_stride[1]))
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "global_pooling": global_pooling, "strides": pool_stride,
               "paddings": pool_padding, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               fuse_with_relu=False):
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(attr=helper.param_attr, shape=param_shape,
                                    dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=param_shape,
                                   dtype=dtype, is_bias=True)
    from .. import unique_name
    # moving stats must have stable saveable names — an anonymous @TEMP@
    # persistable cannot round-trip through save/load_inference_model
    mean = helper.create_global_variable(
        name=moving_mean_name or unique_name.generate(
            helper.name + ".w_mean"),
        dtype=dtype, shape=param_shape, persistable=True)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name or unique_name.generate(
            helper.name + ".w_variance"),
        dtype=dtype, shape=param_shape,
        persistable=True)
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input_shape
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(attr=helper.param_attr, shape=param_shape,
                                    dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(attr=helper.bias_attr, shape=param_shape,
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input_shape
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean_out],
                              "Variance": [var_out]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(attr=helper.param_attr, shape=[c],
                                    dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr, shape=[c],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean_out],
                              "Variance": [var_out]},
                     attrs={"epsilon": epsilon, "groups": groups})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    mask.shape = x.shape
    helper.append_op(
        type="dropout", inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "seed": seed if seed is not None else 0,
               "dropout_implementation": dropout_implementation})
    return out


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape:
        out.shape = tuple(input.shape[:-1]) + (1,)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    softmax_out.shape = logits.shape
    loss = helper.create_variable_for_type_inference(logits.dtype)
    if logits.shape:
        loss.shape = tuple(logits.shape[:-1]) + (1,)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [softmax_out], "Loss": [loss]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", **locals())
    minus_out = helper.create_variable_for_type_inference(input.dtype)
    minus_out.shape = input.shape
    helper.append_op(type="elementwise_sub",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [minus_out]})
    square_out = helper.create_variable_for_type_inference(input.dtype)
    square_out.shape = input.shape
    helper.append_op(type="square", inputs={"X": [minus_out]},
                     outputs={"Out": [square_out]})
    return square_out


def mean(x, name=None):
    helper = LayerHelper("mean", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = (1,)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape and y.shape:
        out.shape = tuple(x.shape[:x_num_col_dims]) + tuple(y.shape[y_num_col_dims:])
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape and y.shape:
        xs = list(x.shape)
        ys = list(y.shape)
        if transpose_x and len(xs) >= 2:
            xs[-1], xs[-2] = xs[-2], xs[-1]
        if transpose_y and len(ys) >= 2:
            ys[-1], ys[-2] = ys[-2], ys[-1]
        if len(xs) >= 2 and len(ys) >= 2:
            out.shape = tuple(xs[:-1]) + (ys[-1],)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def _binary_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(x.dtype)
        out.shape = x.shape
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out)
    layer.__name__ = op_type
    return layer


elementwise_add = _binary_layer("elementwise_add")
elementwise_sub = _binary_layer("elementwise_sub")
elementwise_mul = _binary_layer("elementwise_mul")
elementwise_div = _binary_layer("elementwise_div")
elementwise_max = _binary_layer("elementwise_max")
elementwise_min = _binary_layer("elementwise_min")
elementwise_pow = _binary_layer("elementwise_pow")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if input.shape is not None:
            s = list(input.shape)
            dims = dim if dim is not None else list(range(len(s)))
            if isinstance(dims, int):
                dims = [dims]
            dims = [d % len(s) for d in dims]
            if keep_dim:
                ns = [1 if i in dims else v for i, v in enumerate(s)]
            else:
                ns = [v for i, v in enumerate(s) if i not in dims]
            out.shape = tuple(ns) if ns else (1,)
        helper.append_op(
            type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
            attrs={"dim": dim if isinstance(dim, (list, tuple)) or dim is None
                   else [dim],
                   "keep_dim": keep_dim, "reduce_all": dim is None})
        return out
    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None and -1 not in x.shape:
        total = int(np.prod(x.shape))
        s = [x.shape[i] if v == 0 else v for i, v in enumerate(shape)]
        if -1 in s:
            known = int(np.prod([v for v in s if v != -1]))
            s[s.index(-1)] = total // known
        out.shape = tuple(s)
    else:
        out.shape = tuple(shape)
    helper.append_op(type="reshape", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        out.shape = tuple(x.shape[p] for p in perm)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    input_shape = input.shape
    dim_ = dim % len(input_shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        sizes = [input_shape[dim_] // num] * num if input_shape[dim_] not in (-1, None) else [-1] * num
    else:
        sections = list(num_or_sections)
        num = 0
        sizes = sections
    outs = []
    for sz in sizes:
        o = helper.create_variable_for_type_inference(input.dtype)
        s = list(input_shape)
        s[dim_] = sz
        o.shape = tuple(s)
        outs.append(o)
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"num": num, "sections": sections, "axis": dim_})
    return outs


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64",
                                                        stop_gradient=True)
    if input.shape is not None:
        s = tuple(input.shape[:-1]) + (k,)
        values.shape = s
        indices.shape = s
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    values.stop_gradient = True
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot", **locals())
    out = helper.create_variable_for_type_inference("float32")
    if input.shape is not None:
        s = list(input.shape)
        if s and s[-1] == 1:
            s = s[:-1]
        out.shape = tuple(s) + (depth,)
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    out.shape = input.shape
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from . import ops as _ops

    if axis < 0:
        axis = len(x.shape) + axis
    sq = elementwise_mul(x, x)
    ssum = reduce_sum(sq, dim=axis, keep_dim=True)
    norm = _ops.sqrt(scale(ssum, bias=epsilon))
    return elementwise_div(x, norm)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    if epsilon > 1.0 or epsilon < 0.0:
        raise ValueError("epsilon must be in [0, 1]")
    n_classes = label.shape[-1]
    smoothed = scale(label, scale=1.0 - epsilon,
                     bias=epsilon / n_classes if prior_dist is None else 0.0)
    if prior_dist is not None:
        smoothed = elementwise_add(smoothed, scale(prior_dist, scale=epsilon))
    return smoothed


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1", **locals())
    diff = helper.create_variable_for_type_inference(x.dtype,
                                                     stop_gradient=True)
    diff.shape = x.shape
    loss = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        loss.shape = (x.shape[0], 1)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma if sigma is not None else 1.0})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", **locals())
    loss = helper.create_variable_for_type_inference(input.dtype)
    loss.shape = input.shape
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [loss]}, attrs={"epsilon": epsilon})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", **locals())
    residual = helper.create_variable_for_type_inference(input.dtype,
                                                         stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    residual.shape = input.shape
    out.shape = input.shape
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": delta})
    return out


def dice_loss(input, label, epsilon=1e-5):
    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dims)
    dice_denominator = elementwise_add(reduce_sum(input, dim=reduce_dims),
                                       reduce_sum(label, dim=reduce_dims))
    dice_score = scale(elementwise_div(
        scale(inse, scale=2.0),
        scale(dice_denominator, bias=epsilon)), scale=-1.0, bias=1.0)
    return reduce_mean(dice_score)


def gather(input, index):
    helper = LayerHelper("gather", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape is not None and index.shape is not None:
        out.shape = (index.shape[0],) + tuple(input.shape[1:])
    helper.append_op(type="gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        out.shape = tuple(
            -1 if d in (-1, None) else d + paddings[2 * i] + paddings[2 * i + 1]
            for i, d in enumerate(x.shape))
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape is not None:
        n, c, h, w = input.shape
        if data_format == "NCHW":
            out.shape = (n, c,
                         -1 if h in (-1, None) else h + paddings[0] + paddings[1],
                         -1 if w in (-1, None) else w + paddings[2] + paddings[3])
    helper.append_op(type="pad2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value),
                            "data_format": data_format})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", **locals())
    out = helper.create_variable_for_type_inference(y.dtype)
    out.shape = x.shape
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
                     attrs={"pad_value": float(pad_value)})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape is not None:
        s = [d for i, d in enumerate(input.shape)
             if not (i in [a % len(input.shape) for a in axes] and d == 1)] \
            if axes else [d for d in input.shape if d != 1]
        out.shape = tuple(s)
    helper.append_op(type="squeeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": axes})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape is not None:
        s = list(input.shape)
        for a in sorted(axes):
            s.insert(a, 1)
        out.shape = tuple(s)
    helper.append_op(type="unsqueeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": axes})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    if isinstance(x, Variable):
        x = [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    if x[0].shape is not None:
        s = list(x[0].shape)
        s.insert(axis % (len(s) + 1), len(x))
        out.shape = tuple(s)
    helper.append_op(type="stack", inputs={"X": x}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = []
    s = list(x.shape)
    del s[axis % len(s)]
    for _ in range(num):
        o = helper.create_variable_for_type_inference(x.dtype)
        o.shape = tuple(s)
        outs.append(o)
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        out.shape = tuple(-1 if d in (-1, None) else d * t
                          for d, t in zip(x.shape, expand_times))
    helper.append_op(type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    if input.shape is not None:
        s = list(input.shape)
        for a, st, e in zip(axes, starts, ends):
            if s[a] in (-1, None):
                continue
            st_ = st + s[a] if st < 0 else min(st, s[a])
            e_ = e + s[a] if e < 0 else min(e, s[a])
            s[a] = max(e_ - st_, 0)
        out.shape = tuple(s)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32",
                                                    stop_gradient=True)
    out.shape = (len(input.shape),)
    helper.append_op(type="shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        lead = x.shape[:axis]
        rest = x.shape[axis:]
        l = -1 if any(d in (-1, None) for d in lead) else int(np.prod(lead)) if lead else 1
        r = -1 if any(d in (-1, None) for d in rest) else int(np.prod(rest)) if rest else 1
        out.shape = (l, r)
    helper.append_op(type="flatten", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", **locals())
    filter_size = _to_list(filter_size, 2)
    stride = _to_list(stride, 2)
    padding = _to_list(padding, 2)
    if len(padding) == 2:
        padding = padding * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": filter_size, "strides": stride,
                            "paddings": padding})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None:
        n, c, h, w = x.shape
        out.shape = (n, c // groups, h, w)
    helper.append_op(type="maxout", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"groups": groups})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def log(x, name=None):
    helper = LayerHelper("log", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="log", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", **locals())
    if isinstance(shape, Variable):
        raise NotImplementedError("dynamic crop shape not supported on TPU")
    offsets = offsets or [0] * len(x.shape)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(shape)
    helper.append_op(type="crop", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "offsets": list(offsets)})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou", **locals())
    out_mean_iou = helper.create_variable_for_type_inference("float32",
                                                             stop_gradient=True)
    out_wrong = helper.create_variable_for_type_inference("float32",
                                                          stop_gradient=True)
    out_correct = helper.create_variable_for_type_inference("float32",
                                                            stop_gradient=True)
    helper.append_op(type="mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [out_mean_iou],
                              "OutWrong": [out_wrong],
                              "OutCorrect": [out_correct]},
                     attrs={"num_classes": num_classes})
    return out_mean_iou, out_wrong, out_correct


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR"):
    helper = LayerHelper("image_resize", **locals())
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op_type = "bilinear_interp" if resample == "BILINEAR" else "nearest_interp"
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (input.shape[0], input.shape[1], out_shape[0], out_shape[1])
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_h": out_shape[0], "out_w": out_shape[1]})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR")


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    if mode not in ("all", "channel", "element"):
        raise ValueError("mode must be all|channel|element")
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [x.shape[1]]
    elif mode == "element":
        alpha_shape = [int(np.prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=alpha_shape, dtype="float32",
        is_bias=False, default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def lod_reset(x, y=None, target_lod=None):
    """ref: lod_reset_op.cc — replace x's LoD from y or target_lod."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"target_lod": list(target_lod or [])})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_global_variable(
        name=counter_name, dtype="int64", shape=[1], persistable=True)
    helper.set_variable_initializer(counter,
                                    ConstantInitializer(begin - 1))
    helper.main_program.global_block().append_op(
        type="increment", inputs={"X": [counter]}, outputs={"Out": [counter]},
        attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


# ---------------------------------------------------------------------------
# structured losses (ref: layers/nn.py linear_chain_crf/crf_decoding/nce/
# hsigmoid/warpctc/edit_distance/ctc_greedy_decoder)
# ---------------------------------------------------------------------------


def linear_chain_crf(input, label, param_attr=None):
    """ref: layers/nn.py linear_chain_crf — emission + learned transition
    ([start; end; A] rows, crf_decoding_op.cc doc)."""
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[1]
    transition = helper.create_parameter(attr=helper.param_attr,
                                         shape=[size + 2, size],
                                         dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(dtype=input.dtype)
    emission_exps = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    transition_exps = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label]},
        outputs={"Alpha": [alpha], "EmissionExps": [emission_exps],
                 "TransitionExps": [transition_exps],
                 "LogLikelihood": [log_likelihood]})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """ref: layers/nn.py crf_decoding."""
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [viterbi_path]})
    return viterbi_path


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        seed=0):
    """ref: layers/nn.py nce."""
    helper = LayerHelper("nce", **locals())
    if sample_weight is not None:
        raise NotImplementedError("nce: sample_weight is not supported")
    dim = input.shape[1]
    num_neg_samples = int(num_neg_samples or 10)
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[num_total_classes, 1],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(dtype=input.dtype)
    sample_logits = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    sample_labels = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="nce",
        inputs={"Input": [input], "Label": [label], "Weight": [w],
                "Bias": [b]},
        outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                 "SampleLabels": [sample_labels]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples, "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """ref: layers/nn.py hsigmoid (hierarchical sigmoid over a complete
    binary class tree)."""
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dim = input.shape[1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[1, num_classes - 1],
                                dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pre_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs={"X": [input], "W": [w], "Label": [label], "Bias": [b]},
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": num_classes})
    return out


def warpctc(input, label, blank=0, norm_by_times=False):
    """ref: layers/nn.py warpctc (CTC loss on lod logits/labels)."""
    helper = LayerHelper("warpctc", **locals())
    loss_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    grad_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="warpctc", inputs={"Logits": [input], "Label": [label]},
        outputs={"WarpCTCGrad": [grad_out], "Loss": [loss_out]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss_out


def sequence_erase(input, tokens=None, name=None):
    """Remove listed token values from a LoD sequence tensor (ref:
    layers/nn.py sequence_erase, sequence_erase_op.cc).  Output rows are
    data-dependent, so the op executes as an eager host island."""
    helper = LayerHelper("sequence_erase", **locals())
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype())
    helper.append_op(
        type="sequence_erase", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"tokens": [int(t) for t in (tokens or [])]})
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    """ref: layers/nn.py edit_distance (ignored tokens are erased from
    both hypotheses and references first, via sequence_erase)."""
    helper = LayerHelper("edit_distance", **locals())
    if ignored_tokens:
        input = sequence_erase(input, tokens=ignored_tokens)
        label = sequence_erase(label, tokens=ignored_tokens)
    edit_distance_out = helper.create_variable_for_type_inference(
        dtype="float32")
    sequence_num = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="edit_distance", inputs={"Hyps": [input], "Refs": [label]},
        outputs={"Out": [edit_distance_out], "SequenceNum": [sequence_num]},
        attrs={"normalized": normalized})
    return edit_distance_out, sequence_num


def ctc_greedy_decoder(input, blank, name=None):
    """ref: layers/nn.py ctc_greedy_decoder = argmax + ctc_align."""
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    _, topk_indices = topk(input, k=1)
    ctc_out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="ctc_align", inputs={"Input": [topk_indices]},
        outputs={"Output": [ctc_out]},
        attrs={"merge_repeated": True, "blank": blank})
    return ctc_out


def moe_ffn(input, num_experts, hidden_size, top_k=2, capacity_factor=1.25,
            activation="relu", param_attr=None, name=None):
    """Mixture-of-experts feed-forward with expert parallelism (TPU-native
    capability beyond the reference — SURVEY.md §2.6 lists MoE/EP "Absent";
    see parallel/moe.py).  input: [..., D].  Returns (out [..., D],
    aux_loss scalar) — callers add the Switch load-balancing ``aux_loss``
    (weighted ~1e-2) to their training loss and usually wrap ``out`` in a
    residual connection (dropped-overflow tokens output zero).

    Expert weights carry ``dist_hint="ep"``: under a mesh with an "ep" axis
    the expert dimension shards across it and GSPMD lowers the dispatch
    einsums to all-to-alls over ICI."""
    if top_k > num_experts:
        raise ValueError(
            f"moe_ffn: top_k={top_k} exceeds num_experts={num_experts}")
    from ..initializer import XavierInitializer

    helper = LayerHelper("moe_ffn", **locals())
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    # each create_parameter mutates attr.name — every param needs its own
    # copy or they all collapse onto one var
    _pa = lambda: copy.deepcopy(param_attr)
    gate_w = helper.create_parameter(attr=_pa(), shape=[d, num_experts],
                                     dtype=dtype)
    # stacked expert weights need PER-EXPERT fans — the default fan
    # convention would read the expert dim as part of the receptive field
    w1 = helper.create_parameter(attr=_pa(),
                                 shape=[num_experts, d, hidden_size],
                                 dtype=dtype,
                                 default_initializer=XavierInitializer(
                                     fan_in=d, fan_out=hidden_size))
    b1 = helper.create_parameter(attr=_pa(),
                                 shape=[num_experts, hidden_size],
                                 dtype=dtype, is_bias=True)
    w2 = helper.create_parameter(attr=_pa(),
                                 shape=[num_experts, hidden_size, d],
                                 dtype=dtype,
                                 default_initializer=XavierInitializer(
                                     fan_in=hidden_size, fan_out=d))
    b2 = helper.create_parameter(attr=_pa(), shape=[num_experts, d],
                                 dtype=dtype, is_bias=True)
    for p in (w1, b1, w2, b2):
        p.dist_hint = "ep"
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape)
    aux = helper.create_variable_for_type_inference(dtype)
    aux.shape = ()
    helper.append_op(
        type="moe_ffn",
        inputs={"X": [input], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"top_k": int(top_k), "capacity_factor": float(capacity_factor),
               "activation": activation})
    return out, aux


def gpipe_mlp_stack(input, n_layers, act="relu", n_microbatches=4,
                    pp_axis="pp", param_attr=None, name=None):
    """A stack of ``n_layers`` equal-width fc layers run as a GPipe
    pipeline when the active mesh has a "pp" axis (TPU-native capability —
    SURVEY.md §2.6 lists PP "Absent in Fluid"; see parallel/pipeline.py).
    Single-device the layers apply sequentially: identical math, portable
    programs.  input: [N, D]; weights are stacked [L, D, D] with
    ``dist_hint="pp"`` so each pipeline stage holds only its own layers."""
    from ..initializer import XavierInitializer

    helper = LayerHelper("gpipe_mlp_stack", **locals())
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    w = helper.create_parameter(attr=copy.deepcopy(param_attr),
                                shape=[n_layers, d, d],
                                dtype=dtype,
                                default_initializer=XavierInitializer(
                                    fan_in=d, fan_out=d))
    b = helper.create_parameter(attr=copy.deepcopy(param_attr),
                                shape=[n_layers, d],
                                dtype=dtype, is_bias=True)
    w.dist_hint = "pp"
    b.dist_hint = "pp"
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape)
    helper.append_op(
        type="gpipe_mlp_stack",
        inputs={"X": [input], "W": [w], "B": [b]},
        outputs={"Out": [out]},
        attrs={"act": act, "n_microbatches": int(n_microbatches),
               "pp_axis": pp_axis})
    return out


def ring_attention(q, k, v, causal=False, scale=None, sp_axis="sp",
                   bias=None, flash=None, name=None):
    """Fused attention (TPU-native capability beyond the reference — see
    parallel/ring_attention.py + ops/pallas_flash.py).  q, k, v:
    [B, H, T, D].  Under a mesh with an `sp` axis the sequence dim shards
    across devices and K/V rotate the ICI ring; single-device the executor
    picks the Pallas flash kernel (fwd + bwd VMEM streaming) or XLA full
    softmax.  ``bias``, if given, is an additive [B, 1, 1, T] key bias
    (padding mask).  ``flash``: True forces the Pallas kernel, False
    forbids it, None (default) = auto (TPU backend, PADDLE_TPU_FLASH
    honored — ops/attention_ops._use_flash)."""
    helper = LayerHelper("ring_attention", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype("q"))
    out.shape = tuple(q.shape)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op(
        type="ring_attention", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"causal": causal, "scale": float(scale or 0.0),
               "sp_axis": sp_axis,
               "flash": -1 if flash is None else int(bool(flash))})
    return out

def paged_attention(q, cache_k, cache_v, page_table, bias, scale=1.0,
                    fused=None, name=None):
    """One decode step of attention over a PAGED K/V cache
    (serving/kvpool, ops/decode_ops.py + ops/pallas_paged.py).  q:
    [slots, 1, d_model]; cache_k/cache_v: [num_pages + 1, page_size,
    d_model] page pools (the last row is the trash page); page_table:
    [slots, pages_per_slot] int (unmapped entries point at the trash
    page); bias: [slots, 1, pages_per_slot * page_size] additive
    validity bias with exact ``-inf`` past each slot's live length.
    ``fused``: True forces the Pallas scalar-prefetch gather kernel,
    False the XLA ``take`` fallback, None (default) = PADDLE_TPU_FUSED
    auto.  Returns [slots, 1, d_model]."""
    helper = LayerHelper("paged_attention", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype("q"))
    out.shape = tuple(q.shape)
    helper.append_op(
        type="paged_attention",
        inputs={"Q": [q], "CacheK": [cache_k], "CacheV": [cache_v],
                "PageTable": [page_table], "Bias": [bias]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale),
               "fused": -1 if fused is None else int(bool(fused))})
    return out


def kv_cache_update(cache, new, slots, pos, name=None):
    """Scatter ``new`` [n, w, ...] into rows of the persistable KV cache
    ``cache`` [max_slots, max_len, ...] at per-row destinations: row j
    lands at ``cache[slots[j], pos[j]:pos[j]+w]`` (continuous-batching
    decode, ops/decode_ops.py).  The op writes the cache var IN PLACE
    (its output is ``cache`` itself), so the executor commits it as
    persistent device state after the dispatch — with
    ``program._donate_state`` the buffer is donated and aliased
    window-over-window.  Returns ``cache``.  Callers guarantee
    ``pos + w <= max_len``."""
    helper = LayerHelper("kv_cache_update", **locals())
    helper.append_op(
        type="kv_cache_update",
        inputs={"Cache": [cache], "New": [new], "Slots": [slots],
                "Pos": [pos]},
        outputs={"Out": [cache]})
    return cache


def kv_cache_scatter(cache, new, rows, offs, name=None):
    """Scatter per-token K/V rows ``new`` [n, ...] into the persistable
    cache ``cache`` [rows, width, ...] at explicit destinations: token j
    lands at ``cache[rows[j], offs[j]]`` (speculative verify step,
    ops/decode_ops.py).  Dense caches pass (slot, absolute position);
    paged caches pass (page, in-page offset).  Out-of-range rows are
    scatter-dropped — the dense trash slot.  In-place by name like
    ``kv_cache_update``; returns ``cache``."""
    helper = LayerHelper("kv_cache_scatter", **locals())
    helper.append_op(
        type="kv_cache_scatter",
        inputs={"Cache": [cache], "New": [new], "Rows": [rows],
                "Offs": [offs]},
        outputs={"Out": [cache]})
    return cache


def spec_accept(logits, draft, mask=None, end_id=0, name=None):
    """Greedy speculative acceptance (serving/specdec): given verify
    logits [slots, k+1, vocab] and the k drafted tokens [slots, k],
    return ``(tokens, num_accept)`` — tokens [slots, k+1] int64 is the
    target argmax at every scored position, num_accept [slots] int64 the
    longest draft==argmax prefix.  The engine consumes
    ``tokens[s, :n+1]``, all target argmaxes, so speculative output is
    bitwise identical to sequential greedy decode.  Inactive slots
    (mask == 0) emit ``end_id`` and accept 0."""
    helper = LayerHelper("spec_accept", **locals())
    toks = helper.create_variable_for_type_inference(
        core.convert_dtype("int64"), stop_gradient=True)
    toks.shape = tuple(logits.shape[:-1])
    nacc = helper.create_variable_for_type_inference(
        core.convert_dtype("int64"), stop_gradient=True)
    nacc.shape = (logits.shape[0],)
    inputs = {"Logits": [logits], "Draft": [draft]}
    if mask is not None:
        inputs["Mask"] = [mask]
    helper.append_op(type="spec_accept", inputs=inputs,
                     outputs={"Tokens": [toks], "NumAccept": [nacc]},
                     attrs={"end_id": int(end_id)})
    return toks, nacc


def token_select(logits, mask=None, end_id=0, name=None):
    """Greedy per-slot next-token choice for the compiled decode step:
    ``argmax(logits, -1)`` where ``mask`` is truthy, ``end_id``
    otherwise (inactive/free slots emit inert pad tokens).  logits:
    [slots, vocab]; mask: optional [slots].  Returns [slots] int64."""
    helper = LayerHelper("token_select", **locals())
    out = helper.create_variable_for_type_inference(
        core.convert_dtype("int64"), stop_gradient=True)
    out.shape = tuple(logits.shape[:-1])
    inputs = {"Logits": [logits]}
    if mask is not None:
        inputs["Mask"] = [mask]
    helper.append_op(type="token_select", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"end_id": int(end_id)})
    return out


def _stack_params(helper, dtype, n_layer, d_model, d_inner, decoder,
                  param_attr):
    """Create the stacked [L, ...] parameters of a transformer layer stack,
    tagged with per-dim ``dist_spec`` mesh hints (parallel/transformer_stack
    .dist_spec_for) so pp shards layers and mp shards the Megatron dims."""
    from ...parallel import transformer_stack as ts
    from ..initializer import ConstantInitializer, XavierInitializer

    table = ts.DECODER_SLOTS if decoder else ts.ENCODER_SLOTS
    shapes = {
        "WQ": [n_layer, d_model, d_model], "WK": [n_layer, d_model, d_model],
        "WV": [n_layer, d_model, d_model], "WO": [n_layer, d_model, d_model],
        "FFN1W": [n_layer, d_model, d_inner], "FFN1B": [n_layer, d_inner],
        "FFN2W": [n_layer, d_inner, d_model], "FFN2B": [n_layer, d_model],
        "LN1S": [n_layer, d_model], "LN1B": [n_layer, d_model],
        "LN2S": [n_layer, d_model], "LN2B": [n_layer, d_model],
    }
    if decoder:
        shapes.update({
            "CQ": [n_layer, d_model, d_model], "CK": [n_layer, d_model, d_model],
            "CV": [n_layer, d_model, d_model], "CO": [n_layer, d_model, d_model],
            "LN3S": [n_layer, d_model], "LN3B": [n_layer, d_model],
        })
    params = {}
    for slot, shape in shapes.items():
        if slot.endswith(("S",)) and slot.startswith("LN"):
            init = ConstantInitializer(1.0)
        elif slot.endswith("B") or len(shape) == 2:
            init = ConstantInitializer(0.0)
        else:
            # stacked weights need PER-LAYER fans: the default fan
            # convention would read the layer dim as receptive field
            init = XavierInitializer(fan_in=shape[1], fan_out=shape[2])
        p = helper.create_parameter(attr=copy.deepcopy(param_attr),
                                    shape=shape, dtype=dtype,
                                    default_initializer=init)
        p.dist_spec = ts.dist_spec_for(slot, len(shape), decoder)
        params[slot] = p
    return params


def transformer_encoder_stack(input, bias=None, n_layer=2, n_head=4,
                              d_inner=None, dropout=0.0, is_test=False,
                              n_microbatches=4, recompute=False,
                              flash=None, param_attr=None, name=None):
    """A full transformer ENCODER stack as one mesh-aware op (TPU-native
    capability — see parallel/transformer_stack.py).  input: [N, T, D];
    bias: optional [N, 1, 1, T] additive key bias (padding mask).

    Single-device this is a lax.scan over the stacked layer params; under a
    mesh it composes pipeline ("pp"), Megatron tensor ("mp") and ring-
    attention sequence ("sp") parallelism with data parallelism ("dp") —
    the same program runs on every mesh shape.  Residual dropout only (see
    transformer_stack module docstring)."""
    helper = LayerHelper("transformer_encoder_stack", **locals())
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    params = _stack_params(helper, dtype, n_layer, d, d_inner or 4 * d,
                           False, param_attr)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape)
    rng_key = helper.create_variable_for_type_inference("int32")
    rng_key.shape = (2,)
    rng_key.stop_gradient = True
    inputs = {"X": [input]}
    if bias is not None:
        inputs["Bias"] = [bias]
    inputs.update({slot: [p] for slot, p in params.items()})
    helper.append_op(
        type="transformer_encoder_stack", inputs=inputs,
        outputs={"Out": [out], "RngKey": [rng_key]},
        attrs={"n_head": int(n_head), "dropout": float(dropout),
               "is_test": bool(is_test),
               "n_microbatches": int(n_microbatches),
               "recompute": bool(recompute),
               "flash": -1 if flash is None else int(bool(flash))})
    return out


def transformer_decoder_stack(input, enc_out, src_bias=None, n_layer=2,
                              n_head=4, d_inner=None, dropout=0.0,
                              is_test=False, n_microbatches=4,
                              recompute=False, flash=None,
                              param_attr=None, name=None):
    """A full transformer DECODER stack (causal self-attn + cross-attn +
    FFN per layer) as one mesh-aware op; see transformer_encoder_stack.
    input: [N, Tt, D]; enc_out: [N, Ts, D]; src_bias: [N, 1, 1, Ts]."""
    helper = LayerHelper("transformer_decoder_stack", **locals())
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    params = _stack_params(helper, dtype, n_layer, d, d_inner or 4 * d,
                           True, param_attr)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape)
    rng_key = helper.create_variable_for_type_inference("int32")
    rng_key.shape = (2,)
    rng_key.stop_gradient = True
    inputs = {"X": [input], "EncOut": [enc_out]}
    if src_bias is not None:
        inputs["Bias"] = [src_bias]
    inputs.update({slot: [p] for slot, p in params.items()})
    helper.append_op(
        type="transformer_decoder_stack", inputs=inputs,
        outputs={"Out": [out], "RngKey": [rng_key]},
        attrs={"n_head": int(n_head), "dropout": float(dropout),
               "is_test": bool(is_test),
               "n_microbatches": int(n_microbatches),
               "recompute": bool(recompute),
               "flash": -1 if flash is None else int(bool(flash))})
    return out


def cos_sim(X, Y, name=None):
    """Cosine similarity per row (ref: layers/nn.py cos_sim, cos_sim_op.*)."""
    helper = LayerHelper("cos_sim", **locals())
    dtype = helper.input_dtype("X")
    out = helper.create_variable_for_type_inference(dtype)
    xn = helper.create_variable_for_type_inference(dtype)
    yn = helper.create_variable_for_type_inference(dtype)
    out.shape = (X.shape[0], 1)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]})
    return out


def multiplex(inputs, index):
    """Row-wise select across candidate tensors (ref multiplex_op.*)."""
    helper = LayerHelper("multiplex", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("inputs"))
    out.shape = tuple(inputs[0].shape)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    """3-D pooling (ref pool_op.* 3-D registration)."""
    helper = LayerHelper("pool3d", **locals())
    pool_size = _to_list(pool_size, 3)
    pool_stride = _to_list(pool_stride, 3)
    pool_padding = _to_list(pool_padding, 3)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    dims = input.shape

    def _po(size, k, pad, st):
        if size in (-1, None):
            return -1
        if ceil_mode:
            return (size - k + 2 * pad + st - 1) // st + 1
        return (size - k + 2 * pad) // st + 1

    if global_pooling:
        out.shape = tuple(dims[:2]) + (1, 1, 1)
    else:
        out.shape = tuple(dims[:2]) + tuple(
            _po(dims[2 + i], pool_size[i], pool_padding[i], pool_stride[i])
            for i in range(3))
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def random_crop(x, shape, seed=None):
    """Per-instance random crops of the trailing dims (ref
    random_crop_op.*)."""
    helper = LayerHelper("random_crop", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    lead = len(x.shape) - len(shape)
    out.shape = tuple(x.shape[:lead]) + tuple(shape)
    seed_out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="random_crop", inputs={"X": [x]},
                     outputs={"Out": [out], "SeedOut": [seed_out]},
                     attrs={"shape": list(shape),
                            "startup_seed": seed or 0})
    return out


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (ref rank_loss_op.*)."""
    helper = LayerHelper("rank_loss", **locals())
    out = helper.create_variable_for_type_inference("float32")
    out.shape = tuple(label.shape)
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len, keeping aspect
    (ref layers/nn.py image_resize_short)."""
    in_shape = input.shape
    if len(in_shape) != 4:
        raise ValueError("image_resize_short expects NCHW input")
    h, w = in_shape[2], in_shape[3]
    # pin the SHORT side exactly; round the long side half-up (ref
    # layers/nn.py image_resize_short)
    if h <= w:
        out_shape = [out_short_len, int(w * out_short_len / h + 0.5)]
    else:
        out_shape = [int(h * out_short_len / w + 0.5), out_short_len]
    return image_resize(input, out_shape=out_shape, resample=resample)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug-print a tensor during execution (ref print_op.cc; runs as a
    host callback in the eager island path)."""
    helper = LayerHelper("Print", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = tuple(input.shape)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"first_n": first_n, "message": message or "",
               "summarize": summarize,
               "print_tensor_name": print_tensor_name,
               "print_tensor_dtype": print_tensor_type,
               "print_tensor_shape": print_tensor_shape})
    return out


def load(out, file_path, load_as_fp16=False):
    """In-graph load of one variable from disk (ref load_op.cc:24)."""
    helper = LayerHelper("load", **locals())
    helper.append_op(type="load", inputs={}, outputs={"Out": [out]},
                     attrs={"file_path": file_path,
                            "load_as_fp16": load_as_fp16})
    return out

def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """3-D transposed convolution (ref conv3d_transpose registration in
    conv_transpose_op.*)."""
    helper = LayerHelper("conv3d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _to_list(stride, 3)
    padding = _to_list(padding, 3)
    dilation = _to_list(dilation, 3)
    if filter_size is None:
        if output_size is None:
            raise ValueError("need filter_size or output_size")
        output_size = _to_list(output_size, 3)
        dims_in = input.shape
        filter_size = [
            (output_size[i] - (dims_in[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1 for i in range(3)]
    else:
        filter_size = _to_list(filter_size, 3)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    dims = input.shape

    def _out_dim(size, k, pad, st, d):
        if size in (-1, None):
            return -1
        return (size - 1) * st - 2 * pad + d * (k - 1) + 1

    out.shape = (dims[0], num_filters) + tuple(
        _out_dim(dims[2 + i], filter_size[i], padding[i], stride[i],
                 dilation[i]) for i in range(3))
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)

