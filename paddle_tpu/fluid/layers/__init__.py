"""fluid.layers namespace (ref: python/paddle/fluid/layers/__init__.py)."""

from . import (control_flow, detection, device, io,
               layer_function_generator, math_op_patch, metric_op, nn,
               ops, tensor)
from . import learning_rate_scheduler, sequence
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .device import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .layer_function_generator import (  # noqa: F401
    autodoc, deprecated, generate_layer_fn, templatedoc)

math_op_patch.monkey_patch_variable()

__all__ = (control_flow.__all__ + detection.__all__ + device.__all__
           + io.__all__ + metric_op.__all__ + nn.__all__
           + ops.__all__ + tensor.__all__ + learning_rate_scheduler.__all__
           + sequence.__all__)
