"""Learning-rate decay schedules (ref: layers/learning_rate_scheduler.py —
exponential/natural_exp/inverse_time/polynomial/piecewise/noam decay).

Each schedule is a small in-graph expression over the auto-incremented global
step counter, so it compiles into the same XLA program as the train step.
"""

from __future__ import annotations

import math

from .nn import autoincreased_step_counter, elementwise_div, elementwise_min, \
    elementwise_max
from .tensor import cast, fill_constant
from . import ops as _ops

__all__ = ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
           "polynomial_decay", "piecewise_decay", "noam_decay",
           "cosine_decay", "append_LARS"]


def _decayed_lr_var(value):
    from ..layer_helper import LayerHelper

    helper = LayerHelper("learning_rate_decay")
    lr = helper.create_global_variable(
        name=helper.name + ".lr", shape=[1], dtype="float32",
        persistable=True)
    return lr


def _global_step():
    counter = autoincreased_step_counter(begin=1)
    return cast(counter, "float32")


def noam_decay(d_model, warmup_steps):
    global_step = _global_step()
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    return (d_model ** -0.5) * elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _global_step()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = _ops.floor(div_res)
    return learning_rate * (float(decay_rate) ** div_res)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _global_step()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = _ops.floor(div_res)
    return learning_rate * _ops.exp(div_res * float(-decay_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _global_step()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = _ops.floor(div_res)
    return learning_rate / (div_res * float(decay_rate) + 1.0)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _global_step()
    if cycle:
        div_res = _ops.ceil(global_step / float(decay_steps))
        # at step 0 paddle forces one cycle
        decay_steps_var = div_res * float(decay_steps)
        p = global_step / decay_steps_var
    else:
        p = elementwise_min(global_step / float(decay_steps),
                            fill_constant([1], "float32", 1.0))
    return (learning_rate - end_learning_rate) * ((1.0 - p) ** power) \
        + end_learning_rate


def piecewise_decay(boundaries, values):
    """lr = values[i] for step in (boundaries[i-1], boundaries[i]].

    Branch-free: a sum of masked constants (TPU-friendly; no lax.cond)."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    global_step = _global_step()
    lr = fill_constant([1], "float32", values[-1])
    prev_bound = None
    for i, b in enumerate(boundaries):
        below = cast(global_step <= float(b), "float32")
        if prev_bound is not None:
            above = cast(global_step > float(prev_bound), "float32")
            mask = below * above
        else:
            mask = below
        lr = lr + mask * (values[i] - values[-1])
        prev_bound = b
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    global_step = _global_step()
    cur_epoch = _ops.floor(global_step / float(step_each_epoch))
    return learning_rate * 0.5 * (
        _ops.cos(cur_epoch * (math.pi / float(epochs))) + 1.0)


def append_LARS(params_grads, learning_rate, weight_decay):
    """LARS — layer-wise adaptive rate scaling (ref layers/
    learning_rate_scheduler.py append_LARS): per parameter,
    lr = global_lr * ||param|| / (||grad|| + weight_decay * ||param||),
    stored back on param.optimize_attr for _create_param_lr to pick up."""
    from . import nn as _nn
    from . import ops as _ops

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return _nn.elementwise_add(grad_norm, param_norm)
        return _nn.elementwise_add(
            grad_norm, _nn.scale(param_norm, scale=float(weight_decay)))

    for param, grad in params_grads:
        if grad is None:
            continue
        attr = param.optimize_attr or {}
        param_lr = attr.get("learning_rate", 1.0)
        param_norm = _ops.sqrt(_nn.reduce_sum(_ops.square(param)))
        grad_norm = _ops.sqrt(_nn.reduce_sum(_ops.square(grad)))
        if isinstance(param_lr, (int, float)):
            scaled = learning_rate if param_lr == 1.0 else \
                _nn.scale(learning_rate, scale=float(param_lr))
        else:  # a Variable (e.g. a prior LARS pass): compose, like the ref
            scaled = _nn.elementwise_mul(learning_rate, param_lr)
        decayed = _nn.elementwise_div(
            _nn.elementwise_mul(scaled, param_norm),
            _balanced_weight(param_norm, grad_norm))
        attr["learning_rate"] = decayed
        param.optimize_attr = attr
