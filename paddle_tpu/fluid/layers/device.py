"""Device-placement layer (ref: python/paddle/fluid/layers/device.py —
get_places feeds ParallelDo's place list).  On this substrate the device
list is the visible jax devices; the op (ops/misc_ops.py get_places)
returns their count/kind for the ParallelDo disposition."""

from ..layer_helper import LayerHelper

__all__ = ["get_places"]


def get_places(device_count=None, device_type=None):
    helper = LayerHelper("get_places")
    out = helper.create_variable_for_type_inference(dtype="int64")
    out.stop_gradient = True
    attrs = {}
    if device_count is not None:
        attrs["device_count"] = int(device_count)
    if device_type is not None:
        attrs["device_type"] = str(device_type)
    helper.append_op(type="get_places", outputs={"Out": [out]},
                     attrs=attrs)
    return out
