"""Control-flow layers (ref: python/paddle/fluid/layers/control_flow.py:30 —
While, Switch, IfElse, DynamicRNN, StaticRNN, ParallelDo).

TPU design: data-dependent control flow must be expressed as
``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` inside one XLA program; the
sub-block ops are traced into the loop body.  This module currently covers
the scalar helpers; While/StaticRNN land with the sequence/RNN milestone.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["increment", "is_empty", "less_than", "equal", "array_length"]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out.shape = x.shape
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool",
                                                         stop_gradient=True)
        cond.shape = x.shape
    helper.append_op(type="less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool",
                                                         stop_gradient=True)
        cond.shape = x.shape
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def is_empty(x, cond=None):
    raise NotImplementedError("is_empty requires dynamic shapes; "
                              "not supported in the XLA trace yet")


def array_length(array):
    raise NotImplementedError("LoDTensorArray lands with the RNN milestone")
