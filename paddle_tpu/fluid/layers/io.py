"""IO layers: data declarations (ref: python/paddle/fluid/layers/io.py:38).

py_reader / double_buffer live in reader-land; on TPU the host->device
pipeline is handled by the executor's async dispatch, so ``data`` is the load-
bearing part of this module and the reader layers are thin compat shims.
"""

from __future__ import annotations

from .. import core
from ..framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    return block.create_var(
        name=name, shape=shape, dtype=core.convert_dtype(dtype),
        lod_level=lod_level, stop_gradient=stop_gradient, is_data=True)
