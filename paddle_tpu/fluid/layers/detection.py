"""Detection layer API (ref: python/paddle/fluid/layers/detection.py —
prior_box :449, box_coder :129, iou_similarity :109, bipartite_match :584,
target_assign :651, multiclass_nms-in-detection_output :93, ssd_loss :734,
roi_pool lives in layers/nn.py in the reference)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "prior_box", "box_coder", "iou_similarity", "bipartite_match",
    "target_assign", "multiclass_nms", "detection_output", "roi_pool",
    "anchor_generator", "polygon_box_transform",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", **locals())
    dtype = helper.input_dtype("input")
    boxes = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    return boxes, var


def anchor_generator(input, anchor_sizes, aspect_ratios=(1.0,),
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", **locals())
    dtype = helper.input_dtype("input")
    anchors = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "stride": list(stride),
               "offset": offset})
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("target_box"))
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype("x"))
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(
        helper.input_dtype("dist_matrix"))
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [out_weight]},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("bboxes"))
    helper.append_op(
        type="multiclass_nms", inputs={"BBoxes": [bboxes],
                                       "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """ref: layers/detection.py detection_output:93 — decode + NMS."""
    from . import nn as _nn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    helper.append_op(type="polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out
