"""Transpilers (ref: python/paddle/fluid/transpiler/)."""

from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .inference_transpiler import InferenceTranspiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .ps_dispatcher import HashName, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "InferenceTranspiler", "memory_optimize", "release_memory",
           "HashName", "RoundRobin"]
