"""Weight-only int8 inference transpiler.

The reference quantizes inference graphs through its analysis pipeline
(ref: inference/analysis/, fake_quantize/fake_dequantize ops, QAT flow);
the fp16 analogue is contrib/float16/float16_transpiler.py, which rewrites
weights in the scope and patches the program.  This is the TPU-native
int8 counterpart, specialized to the part that pays off under XLA:

 - weights of matmul/conv ops are stored int8 (4x less HBM, the real
   bottleneck on inference), with a per-output-channel abs-max scale;
 - a ``dequantize_weight`` op materializes the float weight right at the
   consuming op; XLA fuses the cast+scale into the matmul/conv read, so
   activations and accumulation stay float — "weight-only" quantization,
   the standard accuracy-safe recipe (<1%% drop without calibration data).

Scales come from the weights themselves (per-channel abs-max): weight-only
quantization needs no calibration data or QAT observers — the fake_quantize
ops (ops/quant_ops.py) remain the training-time QAT surface, and a QAT'd
model's weights quantize here losslessly since training already pinned them
to the quantization grid.
"""

from __future__ import annotations

import numpy as np

# op type -> (weight input slot, per-output-channel axis of the weight)
_QUANT_TARGETS = {
    "mul": ("Y", 1),        # [in, out]
    "conv2d": ("Filter", 0),  # [out_c, in_c, kh, kw]
    # embeddings: per-row scales; the dominant weight of decode programs.
    # XLA fuses gather+dequant, so int8 rows stream from HBM.
    "lookup_table": ("W", 0),
}


class Int8WeightTranspiler:
    """Rewrite an INFERENCE program + scope for weight-only int8."""

    def __init__(self, min_elements: int = 64):
        # tiny weights (biases folded into mul, 1x1 vectors) aren't worth
        # the dequant op; skip anything smaller than min_elements
        self.min_elements = min_elements

    def transpile(self, program, place=None, scope=None):
        from ..executor import global_scope
        from ..framework import Parameter

        scope = scope or global_scope()
        gb = program.global_block()
        # pass 1 — collect every consuming site across ALL blocks before
        # touching the scope: a shared weight (tied embedding, reused
        # projection) may be consumed in several blocks, and _quantize
        # drops the fp32 copy, so per-block collect-and-rewrite would
        # miss later consumers
        sites = []  # (block, op index, op, slot, wname)
        axes = {}   # wname -> quant axis (consistent per target table)
        weights = {}
        for block in program.blocks:
            for i, op in enumerate(block.ops):
                target = _QUANT_TARGETS.get(op.type)
                if target is None:
                    continue
                slot, axis = target
                names = op.inputs.get(slot) or []
                if len(names) != 1:
                    continue
                wname = names[0]
                if wname not in weights:
                    if not gb._has_var_recursive(wname) or \
                            not isinstance(gb._var_recursive(wname),
                                           Parameter):
                        continue
                    w = scope.get(wname, None)
                    if w is None:
                        continue
                    w = np.asarray(w)
                    if w.size < self.min_elements or \
                            not np.issubdtype(w.dtype, np.floating):
                        continue
                    weights[wname] = w
                    axes[wname] = axis
                elif axes[wname] != axis:
                    continue  # same weight, incompatible channel axis
                sites.append((block, i, op, slot, wname))

        # pass 2 — quantize each weight ONCE and rewrite every consumer
        for wname, w in weights.items():
            self._quantize(gb, scope, wname, w, axes[wname])
        for _, _, op, slot, wname in sites:
            op.inputs[slot] = [wname + "@DEQ"]
        # one dequantize_weight per (block, weight), before its first
        # consumer there (shared by all consumers in that block); insert
        # back-to-front so original indices stay valid
        for block in program.blocks:
            firsts = {}  # wname -> first consumer index in this block
            for b, i, _, _, wname in sites:
                if b is block:
                    firsts[wname] = min(firsts.get(wname, i), i)
            for wname, i in sorted(firsts.items(), key=lambda t: -t[1]):
                block._insert_op(
                    i, type="dequantize_weight",
                    inputs={"X": [wname + "@INT8"],
                            "Scale": [wname + "@SCALE"]},
                    outputs={"Out": [wname + "@DEQ"]},
                    attrs={"quant_axis": axes[wname]})
            if firsts:
                self._patch_owner_ops(program, block, list(firsts))
        return list(weights)

    def _patch_owner_ops(self, program, block, wnames):
        """Sub-block weights (e.g. the step block of a jit_beam_search op,
        or a While body) are pulled into scope through the OWNING op's X
        input list, which was computed at build time against the float
        weights.  Swap the quantized names in so the executor feeds the
        int8 weight + scale instead of the (now dropped) float copy."""
        owner = None
        for b in program.blocks:
            for op in b.ops:
                if op.attr("sub_block") == block.idx:
                    owner = op
                    break
        if owner is None or "X" not in owner.inputs:
            return
        x = [n for n in owner.inputs["X"] if n not in wnames]
        for w in wnames:
            x.extend([w + "@INT8", w + "@SCALE"])
        owner.inputs["X"] = x

    def _quantize(self, block, scope, wname, w, axis):
        """Store int8 weight + per-channel scale in scope/block; drop the
        float original from the scope (that is the memory win)."""
        gb = block.program.global_block()
        reduce_axes = tuple(d for d in range(w.ndim) if d != axis)
        scale = np.abs(w).max(axis=reduce_axes).astype(np.float32)
        scale = np.where(scale > 0, scale, 1.0)
        shape = [1] * w.ndim
        shape[axis] = -1
        q = np.clip(np.round(w / scale.reshape(shape) * 127.0),
                    -127, 127).astype(np.int8)

        wq_name, sc_name = wname + "@INT8", wname + "@SCALE"
        gb.create_var(name=wq_name, shape=tuple(q.shape), dtype="int8",
                      persistable=True)
        gb.create_var(name=sc_name, shape=tuple(scale.shape),
                      dtype="float32", persistable=True)
        dq_name = wname + "@DEQ"
        gb.create_var(name=dq_name, shape=tuple(w.shape), dtype="float32",
                      persistable=False)
        scope.set(wq_name, q)
        scope.set(sc_name, scale)
        scope._values.pop(wname, None)  # the float copy is the memory win
        return dq_name
