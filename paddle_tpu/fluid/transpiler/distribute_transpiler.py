"""DistributeTranspiler: multi-worker training (ref: transpiler/
distribute_transpiler.py:132).

North-star redesign (BASELINE.json): the reference rewrites the program into
send/recv/listen_and_serv RPC ops against parameter servers.  On a TPU pod
the parameter-server role is obsolete — parameters and optimizer state live
sharded/replicated across the same chips that compute, and gradient exchange
is an XLA all-reduce over ICI.  So ``transpile`` does not inject RPC ops;
it records the trainer topology and marks the program for SPMD execution:

 - get_trainer_program(): the program, unchanged op-wise — ParallelExecutor /
   the multihost runner shard the batch over the global mesh
   (trainers × local devices) and GSPMD inserts collectives.
 - get_pserver_program(): raises with guidance — there is no pserver process
   in the TPU deployment; its state-holding role maps onto sharded optimizer
   state (BuildStrategy.ReduceStrategy.Reduce ≈ ZeRO-1).

Async PS semantics (ref listen_and_serv_op.cc:213 RunAsyncLoop) have no
literal SPMD equivalent; ``sync_mode=False`` maps onto the TPU-native form
of the same staleness-for-throughput trade — local SGD with periodic
parameter averaging (parallel.local_sgd.AsyncLocalSGDTrainer), whose
staleness is bounded by the sync period rather than unbounded.
"""

from __future__ import annotations

import os

from ..framework import Program, default_main_program


class DistributeTranspilerConfig:
    """ref: distribute_transpiler.py:116."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  mesh=None):
        """Record the trainer topology on the program.  ParallelExecutor
        reads this annotation and joins the coordination service
        (parallel.multihost.init) with the first pserver endpoint as the
        coordinator address — the TPU mapping of the reference's
        gen_nccl_id-over-gRPC bootstrap (gen_nccl_id_op.cc:31).

        ``mesh`` (or the ``PADDLE_TPU_MESH`` env, e.g. ``dp4,tp2``)
        selects the named axis layout the SPMD lowering partitions over;
        unset means the pure data-parallel mesh over all devices."""
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.pserver_endpoints = [e for e in pservers.split(",") if e]
        self._transpiled = True
        mesh_spec = mesh or os.environ.get("PADDLE_TPU_MESH", "").strip() \
            or None
        if mesh_spec is not None:
            # fail at transpile time on a malformed spec, not inside jit
            from ...parallel.mesh import parse_mesh_spec

            parse_mesh_spec(mesh_spec)
        self.mesh_spec = mesh_spec
        self.origin_program._dist_info = {
            "trainer_id": trainer_id,
            "trainers": trainers,
            "coordinator": (self.pserver_endpoints[0]
                            if self.pserver_endpoints else None),
            # sync_mode=False selects the async-PS replacement: local SGD
            # with periodic averaging (parallel.local_sgd) instead of the
            # per-step GSPMD collective program
            "mode": "spmd_ici" if sync_mode else "async_local_sgd",
            # named mesh axes the SPMD lowering shards over ("dp4,tp2");
            # None = the degenerate all-devices dp mesh
            "mesh": mesh_spec,
        }
        # Join the pod NOW: jax.distributed.initialize must run before any
        # JAX computation touches the backend, and in the reference flow
        # transpile() is exactly the pre-startup moment (the gen_nccl_id
        # handshake).  ParallelExecutor re-checks idempotently.
        from ...parallel import multihost as _mh

        _mh.ensure_init(self.origin_program._dist_info)

    def get_trainer_program(self) -> Program:
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        return self.origin_program

    def get_pserver_program(self, endpoint) -> Program:
        raise NotImplementedError(
            "TPU pods have no parameter-server process: parameters and "
            "optimizer state are sharded across the mesh and gradients "
            "all-reduce over ICI.  Launch every host with the trainer "
            "program (see paddle_tpu.parallel for multihost init).")

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        raise NotImplementedError(
            "no pserver startup program in the TPU deployment")
