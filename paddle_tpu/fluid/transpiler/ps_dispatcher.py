"""PS dispatchers (ref: transpiler/ps_dispatcher.py) — kept for API parity;
used only to partition variables when emulating pserver layouts."""

from __future__ import annotations


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = pserver_endpoints
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name, len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist
