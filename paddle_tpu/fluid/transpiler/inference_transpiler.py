"""Inference transpiler (ref: transpiler/inference_transpiler.py — folds
batch-norm into conv weights and fuses activations for inference).

Here the transpile (1) flips train-mode ops to is_test, and (2) runs the
real conv+BN fold pass (fluid.ir ConvBNFuse): per-channel rescale of the
conv filter plus a precomputed bias replaces each inference-mode BN whose
sole input is a conv — the same weight rewrite the reference performs.
Elementwise activation fusion is left to XLA, which does it universally."""

from __future__ import annotations


class InferenceTranspiler:
    def transpile(self, program, place, scope=None):
        """Returns the fused program.  Callers must install the RETURN
        VALUE (the reference transpiler mutates its argument; here the
        pass pipeline's ``to_program()`` owns the write-back, and relying
        on aliasing would silently break the moment a pass clones)."""
        from ..executor import global_scope
        from ..ir import ConvBNFuse, Graph

        scope = scope or global_scope()
        for block in program.blocks:
            for op in block.ops:
                if op.type in ("batch_norm", "dropout"):
                    op.attrs["is_test"] = True
        return ConvBNFuse(scope).apply(Graph(program, 0)).to_program()
