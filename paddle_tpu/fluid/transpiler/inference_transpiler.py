"""Inference transpiler (ref: transpiler/inference_transpiler.py — folds
batch-norm into conv weights, fuses relu).

XLA performs these algebraic fusions during compilation, so the transpile is
behavior-preserving identity plus the is_test switch."""

from __future__ import annotations


class InferenceTranspiler:
    def transpile(self, program, place, scope=None):
        for block in program.blocks:
            for op in block.ops:
                if op.type in ("batch_norm", "dropout"):
                    op.attrs["is_test"] = True
        return program
