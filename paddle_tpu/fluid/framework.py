"""Program IR: Variable / Operator / Block / Program.

TPU-native re-design of the reference's graph-builder layer
(ref: python/paddle/fluid/framework.py:207 Variable, :496 Operator, :923 Block,
:1407 Program, over C++ ProgramDesc protobufs in framework.proto:24-194).

Differences from the reference, by design:
 - The IR lives in Python (plain objects, cheaply clonable/serializable); there
   is no mutable C++ desc mirror because execution does not interpret the IR
   op-by-op — the Executor traces a whole block into ONE jitted XLA program
   (see executor.py), so the IR only needs to be a faithful build-time record.
 - Shapes may contain -1 (batch); concrete shapes are bound at trace time from
   the fed arrays, which is what makes one Program servable at many batch
   sizes (one XLA executable per shape signature).
"""

from __future__ import annotations

import contextlib
import copy
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from . import core, unique_name

GRAD_VAR_SUFFIX = "@GRAD"
TEMP_VAR_NAME = "@TEMP@"
RNG_STATE_VAR = "@RNG_STATE@"


class OpRole:
    """Op role attr consumed by transpilers/parallel pass (ref: op_proto_maker.h)."""

    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256

    KEY = "op_role"
    VAR_KEY = "op_role_var"


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX


class Variable:
    """A named value in a Block (ref: framework.py:207).

    Dense LoD tensors carry an optional host-side LoD (list of offset lists);
    on device everything is a static-shape array.
    """

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 lod_level=0, persistable=False, stop_gradient=False,
                 is_data=False, type=core.VarType.LOD_TENSOR, error_clip=None,
                 **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate(TEMP_VAR_NAME)
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = core.convert_dtype(dtype) if type == core.VarType.LOD_TENSOR else dtype
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type
        self.error_clip = error_clip

    # -- paddle API parity helpers --
    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def to_string(self, throw_on_error=False, with_details=False):
        return (f"var {self.name} : shape{self.shape} dtype={self.dtype} "
                f"persistable={self.persistable} stop_gradient={self.stop_gradient}")

    __repr__ = __str__ = lambda self: self.to_string()

    def _clone_into(self, block):
        v = copy.copy(self)
        v.block = block
        return v


class Parameter(Variable):
    """Trainable persistable variable (ref: framework.py:2029)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter needs shape and dtype")
        kwargs.setdefault("persistable", True)
        kwargs.setdefault("stop_gradient", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)


class Operator:
    """One op in a block: type + named input/output slots + attrs
    (ref: framework.py:496 over OpDesc, framework.proto:42)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = _normalize_slot_map(inputs)
        self.outputs: Dict[str, List[str]] = _normalize_slot_map(outputs)
        self.attrs: Dict[str, object] = dict(attrs or {})
        self.attrs.setdefault(OpRole.KEY, OpRole.Forward)

    def input(self, slot) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    set_attr = _set_attr

    def has_attr(self, name):
        return name in self.attrs

    def _rename_input(self, old, new):
        for slot, names in self.inputs.items():
            self.inputs[slot] = [new if n == old else n for n in names]
        self.block.program._bump_version()

    def _rename_output(self, old, new):
        for slot, names in self.outputs.items():
            self.outputs[slot] = [new if n == old else n for n in names]
        self.block.program._bump_version()

    def to_string(self, throw_on_error=False):
        ins = ", ".join(f"{k}={v}" for k, v in sorted(self.inputs.items()))
        outs = ", ".join(f"{k}={v}" for k, v in sorted(self.outputs.items()))
        sig_attrs = {k: v for k, v in self.attrs.items()
                     if k not in (OpRole.KEY, OpRole.VAR_KEY)}
        return f"{{{outs}}} = {self.type}(inputs=[{ins}], attrs={sig_attrs})"

    __repr__ = __str__ = lambda self: self.to_string()


def _normalize_slot_map(m) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = OrderedDict()
    if not m:
        return out
    for slot, vals in m.items():
        if vals is None:
            out[slot] = []
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        names = []
        for v in vals:
            if v is None:
                continue
            names.append(v.name if isinstance(v, Variable) else str(v))
        out[slot] = names
    return out


class Block:
    """Ordered ops + var table; blocks nest for control flow
    (ref: framework.py:923, BlockDesc framework.proto:177)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = OrderedDict()
        self.ops: List[Operator] = []
        # forward-block link used by grad ops of sub-blocks
        self.forward_block_idx = -1

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # ---- vars ----
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, **kwargs) -> Parameter:
        p = Parameter(self, **kwargs)
        # parameters always live in the outermost (global) block
        gb = self.program.global_block()
        p.block = gb
        gb.vars[p.name] = p
        self.program._bump_version()
        return p

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"var {name} not in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _var_recursive(self, name: str) -> Variable:
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        raise ValueError(f"var {name} not found from block {self.idx} upward")

    def _has_var_recursive(self, name: str) -> bool:
        b = self
        while b is not None:
            if name in b.vars:
                return True
            b = b.parent_block
        return False

    def _remove_var(self, name: str):
        self.vars.pop(name, None)
        self.program._bump_version()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # ---- ops ----
    def append_op(self, type=None, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self.program._bump_version()
        return op

    def _prepend_op(self, type=None, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type=None, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def to_string(self, throw_on_error=False, with_details=False):
        lines = [f"-- block {self.idx} (parent {self.parent_idx}) --"]
        for v in self.vars.values():
            lines.append("  " + v.to_string())
        for op in self.ops:
            lines.append("  " + op.to_string())
        return "\n".join(lines)


class Program:
    """A whole computation: list of blocks (ref: framework.py:1407).

    ``_version`` is bumped on every mutation; the Executor keys its
    trace/compile cache on (program, version, shape signature).
    """

    _token_counter = 0

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        # monotonic process-wide identity token: Executor caches key on this
        # instead of id(program), which a freed clone's recycled id could
        # alias into a stale compiled entry (ADVICE r5).  clone()/_prune()
        # build fresh Programs, so derived programs get their own token.
        Program._token_counter += 1
        self._cache_token = Program._token_counter
        self._seed_counter = 0
        # set by optimizer.minimize / append_backward for transpilers
        self._params_grads = None
        self._is_test = False

    # ---- structure ----
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump_version()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def next_seed(self) -> int:
        """Deterministic per-op seed stream derived from random_seed."""
        self._seed_counter += 1
        return self._seed_counter

    # ---- iteration helpers ----
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_ops(self):
        for b in self.blocks:
            yield from b.ops

    # ---- clone / prune ----
    def clone(self, for_test=False) -> "Program":
        p = Program()
        p.random_seed = self.random_seed
        p._seed_counter = self._seed_counter
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            nb.forward_block_idx = b.forward_block_idx
            for v in b.vars.values():
                nb.vars[v.name] = v._clone_into(nb)
            for op in b.ops:
                nop = Operator(nb, op.type, copy.deepcopy(op.inputs),
                               copy.deepcopy(op.outputs), copy.deepcopy(op.attrs))
                if for_test and "is_test" in _TEST_MODE_OPS.get(op.type, ()):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        p.current_block_idx = 0
        p._is_test = for_test
        if for_test:
            for b in p.blocks:
                b.ops = [op for op in b.ops
                         if op.attr(OpRole.KEY, OpRole.Forward) & OpRole.Backward == 0
                         and op.attr(OpRole.KEY, OpRole.Forward) != OpRole.Optimize]
        return p

    def _prune(self, targets, drop_roles=()) -> "Program":
        """Keep only ops needed to produce target vars (ref: prune.cc).
        ``drop_roles``: op-role values removed before slicing (the
        reference's pruning skips backward/optimize ops the same way)."""
        target_names = set()
        for t in targets:
            target_names.add(t.name if isinstance(t, Variable) else str(t))
        drop = 0
        for r in drop_roles:
            drop |= int(r)
        p = self.clone()
        gb = p.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(gb.ops):
            role = int(op.attrs.get(OpRole.KEY, OpRole.Forward))
            if drop and (role & drop):
                continue
            if any(n in needed for n in op.output_arg_names):
                kept.append(op)
                needed.update(op.input_arg_names)
        gb.ops = list(reversed(kept))
        return p

    def inference_optimize(self) -> "Program":
        p = self.clone(for_test=True)
        return p

    # ---- serialization (ref: ProgramDesc proto round-trip —
    # framework.proto:190; the on-wire format here is a versioned pickle,
    # which save/load_inference_model already uses for __model__) ----
    SERIAL_VERSION = 1

    def serialize_to_string(self) -> bytes:
        import pickle

        return pickle.dumps({"version": self.SERIAL_VERSION,
                             "program": self})

    @staticmethod
    def parse_from_string(data: bytes) -> "Program":
        import pickle

        payload = pickle.loads(data)
        if isinstance(payload, Program):  # pre-versioned blobs
            return payload
        if payload.get("version") != Program.SERIAL_VERSION:
            raise ValueError(
                f"program blob version {payload.get('version')} != "
                f"{Program.SERIAL_VERSION}")
        return payload["program"]

    def to_string(self, throw_on_error=False, with_details=False):
        return "\n".join(b.to_string() for b in self.blocks)

    __repr__ = __str__ = lambda self: self.to_string()


# Ops that behave differently under test mode.
_TEST_MODE_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}


# ---------------------------------------------------------------------------
# default programs & guards (ref: framework.py:2047-2158)
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


@contextlib.contextmanager
def name_scope(prefix=None):
    # cosmetic in the reference; kept for parity
    yield


def fresh_session():
    """Reset ALL build-session globals: default programs, unique-name
    counters, global scope.  The single place that knows the full list —
    used by the test fixture, driver entry points, and scripts that build
    several models in one process."""
    from . import executor as _executor
    from . import unique_name as _unique_name

    switch_main_program(Program())
    switch_startup_program(Program())
    _unique_name.switch()
    _executor._global_scope = _executor.Scope()
