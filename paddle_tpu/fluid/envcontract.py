"""Env-knob contract: one declared registry for every ``PADDLE_*`` knob.

The subsystems grown in PRs 1-7 each invented env knobs ad hoc (fault
injection, elastic supervisor, compile cache, observe, AMP, SPMD meshes,
windowed training).  This module is the single source of truth: every knob
is declared here with its type, default and owning subsystem, values are
read through :func:`get` (live — a subprocess that sets the env before
first use is honored, same late-binding contract as ``compile_cache``),
and two pieces of tooling hang off the registry:

 - ``tools/repo_lint.py`` ASTs the tree and fails CI on any
   ``os.environ`` read of a ``PADDLE_*`` key that is not declared here —
   so a typo'd or undocumented knob cannot ship;
 - ``python -m paddle_tpu.fluid.envcontract`` regenerates ``docs/ENV.md``
   (the committed file is diffed against the generator in tier-1, so the
   doc cannot drift from the code).

Declaring is cheap on purpose: ``declare("PADDLE_X", "int", 4, "executor",
"what it does")``.  Families with dynamic suffixes (the PADDLE_FAULT_*
contract) declare each member; :func:`declared` also accepts names covered
by a declared ``prefix`` entry.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = ["EnvKnob", "declare", "get", "get_raw", "declared", "knobs",
           "generate_markdown", "REGISTRY"]

_TYPES = ("str", "int", "float", "bool", "enum", "path", "prefix")

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


@dataclass(frozen=True)
class EnvKnob:
    name: str
    type: str                      # one of _TYPES
    default: object                # the value `get` returns when unset
    subsystem: str                 # owning module family (docs grouping)
    help: str
    choices: Tuple[str, ...] = ()  # for type == "enum"

    def parse(self, raw: Optional[str]):
        """Typed value for a raw env string (None/empty -> default)."""
        if raw is None:
            return self.default
        raw = raw.strip()
        if raw == "":
            return self.default
        if self.type == "int":
            return int(raw)
        if self.type == "float":
            return float(raw)
        if self.type == "bool":
            low = raw.lower()
            if low in _TRUE:
                return True
            if low in _FALSE:
                return False
            return self.default
        if self.type == "enum":
            low = raw.lower()
            return low if low in self.choices else self.default
        return raw  # str / path / prefix


REGISTRY: Dict[str, EnvKnob] = {}


def declare(name: str, type: str, default, subsystem: str, help: str,
            choices: Tuple[str, ...] = ()) -> EnvKnob:
    if type not in _TYPES:
        raise ValueError(f"knob type must be one of {_TYPES}, got {type!r}")
    if name in REGISTRY:
        raise ValueError(f"env knob {name} declared twice")
    knob = EnvKnob(name, type, default, subsystem, help, tuple(choices))
    REGISTRY[name] = knob
    return knob


def get(name: str):
    """Typed live read of a declared knob — unset/empty returns the
    declared default (raises KeyError on undeclared names: reading
    through the contract IS the contract)."""
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(
            f"env knob {name!r} is not declared in fluid.envcontract — "
            f"declare it (name, type, default, subsystem) before reading")
    return knob.parse(os.environ.get(name))


def get_raw(name: str) -> str:
    """The raw (stripped) env string of a declared knob; "" when unset."""
    if name not in REGISTRY and not declared(name):
        raise KeyError(f"env knob {name!r} is not declared")
    return os.environ.get(name, "").strip()


def declared(name: str) -> bool:
    """True if `name` is a declared knob or covered by a prefix family."""
    if name in REGISTRY:
        return True
    return any(k.type == "prefix" and name.startswith(k.name)
               for k in REGISTRY.values())


def knobs() -> List[EnvKnob]:
    return sorted(REGISTRY.values(), key=lambda k: (k.subsystem, k.name))


# ---------------------------------------------------------------------------
# The contract.  Grouped by subsystem; keep help to one line.
# ---------------------------------------------------------------------------

# -- executor / runtime --
declare("PADDLE_EXECUTOR_CACHE_CAP", "int", 64, "executor",
        "Bound on the in-process jit cache (LRU entries)")
declare("PADDLE_TPU_DONATE", "bool", True, "executor",
        "Donate mutable training state to XLA (0 disables, for buffer "
        "lifetime debugging)")
declare("PADDLE_TPU_VERIFY", "enum", "warn", "analysis",
        "Pre-compile program verifier mode", choices=("warn", "strict",
                                                      "off"))
declare("PADDLE_TPU_FLASH", "enum", "auto", "ops",
        "Pallas flash-attention kernel gate: 0 kill-switch wins over "
        "everything, 1 forces on, AUTO = per-op attr then TPU-backend-only",
        choices=("0", "1", "true", "false", "auto"))
declare("PADDLE_TPU_FUSED", "enum", "auto", "ops",
        "Pallas fused-kernel gate (softmax-xent + optimizer sweeps): 0 "
        "restores the unfused XLA lowering, 1 forces on (interpret mode "
        "off-TPU), AUTO = TPU-backend-only",
        choices=("0", "1", "true", "false", "auto"))
declare("PADDLE_TPU_SPD", "int", 0, "trainer",
        "Steps per dispatch: K>1 runs the trainer loop as K-step fused "
        "windows (Executor.run_steps)")
declare("PADDLE_TPU_PREFETCH_DEPTH", "int", 2, "trainer",
        "Device prefetch depth for windowed training (0 = synchronous)")

# -- AMP --
declare("PADDLE_TPU_AMP", "enum", None, "amp",
        "Enable mixed precision at import", choices=("bfloat16", "float16"))
declare("PADDLE_TPU_AMP_KEEP", "bool", False, "amp",
        "Keep activations in the low compute dtype (pure-low regime)")
declare("PADDLE_TPU_AMP_INIT_SCALE", "float", 2.0 ** 15, "amp",
        "Initial dynamic fp16 loss scale")
declare("PADDLE_TPU_AMP_SCALE_INTERVAL", "int", 1000, "amp",
        "Overflow-free steps between loss-scale growth events")

# -- guardian --
declare("PADDLE_TPU_GUARDIAN", "str", None, "guardian",
        "Arm the numerics guardian (skip|halt|dump_and_halt, or 1=skip)")
declare("PADDLE_TPU_GUARDIAN_SPIKE", "float", 0.0, "guardian",
        "Loss-spike rejection factor over the window median (0 = off)")
declare("PADDLE_TPU_GUARDIAN_WINDOW", "int", 32, "guardian",
        "Spike-median window length (steps)")
declare("PADDLE_TPU_GUARDIAN_RING", "int", 128, "guardian",
        "Flight-recorder ring size (steps)")
declare("PADDLE_TPU_GUARDIAN_DIR", "path", None, "guardian",
        "Flight-recorder replay-bundle directory")

# -- SPMD / distributed --
declare("PADDLE_TPU_MESH", "str", None, "parallel",
        "Named mesh spec, e.g. dp4,tp2 (axis order = spec order)")
declare("PADDLE_TRAINERS", "int", 1, "parallel",
        "Process count for the multihost coordination service")
declare("PADDLE_TRAINER_ID", "int", 0, "parallel",
        "This process's rank")
declare("PADDLE_COORDINATOR_ADDR", "str", None, "parallel",
        "host:port of the jax coordination service (process 0)")
declare("PADDLE_PSERVER_EPS", "str", None, "parallel",
        "Legacy pserver endpoint list (transpiler compatibility)")
declare("PADDLE_LOCAL_DEVICE_IDS", "str", None, "parallel",
        "Comma-separated local device ids visible to this process")

# -- elastic supervisor --
declare("PADDLE_TPU_MESH_LADDER", "str", None, "elastic",
        "Semicolon-ordered mesh downgrade ladder, largest first (e.g. "
        "'dp4;dp2;dp1'): after a permanent host loss the supervisor "
        "relaunches on the largest entry the survivor census can run")
declare("PADDLE_ELASTIC_HB_DIR", "path", None, "elastic",
        "Heartbeat directory the supervisor watches (set per generation)")
declare("PADDLE_ELASTIC_INCIDENTS", "path", None, "elastic",
        "incidents.jsonl path guardian trips are appended to")
declare("PADDLE_ELASTIC_GENERATION", "int", 0, "elastic",
        "Elastic generation index of this worker process")

# -- compile cache --
declare("PADDLE_COMPILE_CACHE_DIR", "path", None, "compile_cache",
        "Enable the persistent compile cache, rooted here")
declare("PADDLE_COMPILE_CACHE_BUDGET_MB", "int", None, "compile_cache",
        "LRU size budget over cache entries + the jax xla cache (MB)")

# -- observability --
declare("PADDLE_OBSERVE_DIR", "path", None, "observe",
        "Enable file output (events JSONL + metric snapshots), rooted here")
declare("PADDLE_OBSERVE_FLUSH_S", "float", 5.0, "observe",
        "Metric snapshot flush interval (seconds)")
declare("PADDLE_OBSERVE_PORT", "int", None, "observe",
        "Serve /metrics + /healthz on 127.0.0.1:<port> (0 = ephemeral)")
declare("PADDLE_TRACE", "bool", True, "observe",
        "Span tracing master switch (0 disables all span emission; spans "
        "only materialize when an observe dir is configured)")
declare("PADDLE_TRACE_SAMPLE", "float", 1.0, "observe",
        "Fraction of root spans recorded (deterministic every-Nth "
        "sampling; children follow their root's decision)")
declare("PADDLE_TRACEPARENT", "str", None, "observe",
        "Inherited trace context, W3C-style '00-<trace>-<span>-01' (the "
        "elastic supervisor sets it so worker spans join the run trace)")
declare("PADDLE_SLO", "bool", False, "observe",
        "Arm the SLO watchdog (rolling median+MAD baselines; emits "
        "slo.breach run events on regression)")
declare("PADDLE_SLO_FACTOR", "float", 3.0, "observe",
        "Breach when a value exceeds factor x rolling median (and clears "
        "the MAD noise guard)")
declare("PADDLE_SLO_WINDOW", "int", 64, "observe",
        "Rolling baseline window per watched metric (samples)")
declare("PADDLE_SLO_MIN_SAMPLES", "int", 8, "observe",
        "Baseline samples required before the watchdog may fire")
declare("PADDLE_SLO_COOLDOWN_S", "float", 1.0, "observe",
        "Minimum seconds between breach events for one metric")
declare("PADDLE_GOODPUT", "bool", True, "observe",
        "Arm the always-on goodput accumulator (wall-clock state "
        "counters + goodput.fraction gauge; 0 disables all accounting)")
declare("PADDLE_GOODPUT_REPORT_S", "float", 30.0, "observe",
        "Seconds between periodic goodput.report run events")
declare("PADDLE_GOODPUT_SCAN_S", "float", 5.0, "observe",
        "Elastic supervisor's straggler-scan interval over the fleet "
        "event stream (0 disables the in-flight scan)")
declare("PADDLE_GOODPUT_STRAGGLER_FACTOR", "float", 1.5, "observe",
        "Flag a rank whose median step time exceeds factor x the other "
        "ranks' median (plus their 3xMAD noise guard)")
declare("PADDLE_GOODPUT_MIN_SAMPLES", "int", 4, "observe",
        "Window samples required per rank before the skew test may flag")

# -- serving (continuous-batching decode path) --
declare("PADDLE_SERVE_DECODE", "bool", True, "serving",
        "Continuous-batching decode master switch (0 makes DecodeEngine "
        "construction refuse — the static request-granularity engine "
        "remains the only serving path)")
declare("PADDLE_SERVE_SLOTS", "int", 8, "serving",
        "Decode slots: concurrent KV-cache-resident streams per engine "
        "(the fixed leading dim of the one compiled decode step)")
declare("PADDLE_SERVE_MAX_LEN", "int", 128, "serving",
        "KV-cache capacity per slot (prompt + generated tokens); "
        "admission rejects requests that cannot fit")
declare("PADDLE_SERVE_PREFILL_BUCKETS", "str", "4,8,16", "serving",
        "Comma-separated prompt-length buckets each compiled once; a "
        "prompt pads up to its enclosing bucket (executable set = these "
        "buckets + the one decode step)")
declare("PADDLE_SERVE_SWAP_POLICY", "enum", "drain", "serving",
        "Hot checkpoint swap in-flight policy: drain = resident slots "
        "finish on the old serial (admissions pause, nothing sheds), "
        "immediate = slots continue on the new weights over their old "
        "KV caches", choices=("drain", "immediate"))
declare("PADDLE_SERVE_CANARY_REQUESTS", "int", 0, "serving",
        "Canary probation: completed requests the new serial must serve "
        "under the SLO watchdog + output-sanity sentinel before "
        "promotion (0 = promote immediately, no canary)")
declare("PADDLE_SERVE_SWAP_POLL_S", "float", 2.0, "serving",
        "Model-registry checkpoint-dir watcher poll interval (seconds)")
declare("PADDLE_SERVE_SENTINEL_ENTROPY", "float", 0.05, "serving",
        "Canary sentinel floor (nats): argmax-entropy collapse below "
        "this across 3 consecutive decode ticks triggers auto-rollback")
declare("PADDLE_SERVE_PAGED", "bool", False, "serving",
        "Paged KV cache (serving/kvpool): per-layer K/V storage becomes "
        "a [num_pages, page_size, d_model] page pool with a host-side "
        "allocator and a per-tick page-table feed; 0 (default) keeps the "
        "dense [max_slots, max_len, d_model] cache — the bitwise-restore "
        "kill switch")
declare("PADDLE_SERVE_PAGE_SIZE", "int", 4, "serving",
        "KV-cache page length in token positions; must divide max_len "
        "AND every prefill bucket (prefill scatters whole pages)")
declare("PADDLE_SERVE_NUM_PAGES", "int", 0, "serving",
        "Page-pool capacity in pages (per layer, K+V share the table); "
        "0 = auto: max_slots * max_len / page_size, i.e. dense-equal "
        "capacity — set lower to oversubscribe slots against real usage")
declare("PADDLE_SERVE_PREFIX_SHARE", "bool", True, "serving",
        "Hash-share read-only full-prompt-page K/V across concurrently "
        "resident slots (refcounted; kvpool.prefix_hits counts shared "
        "pages, full-prefix hits skip the prefill dispatch entirely)")
declare("PADDLE_SERVE_SPEC", "int", 0, "serving",
        "Speculative decoding depth k (serving/specdec): each engine "
        "tick runs k cheap draft steps then ONE wide verify step scoring "
        "k+1 positions per slot; greedy acceptance keeps output bitwise "
        "identical to sequential decode. 0 (default) = kill switch, the "
        "plain one-token tick verbatim")
declare("PADDLE_SERVE_SPEC_DRAFT_LAYERS", "int", 1, "serving",
        "Self-draft depth: the draft model reuses the target's first n "
        "decoder layers (+ embeddings/head, shared by name) with its own "
        "dense KV cache; 0 = full-depth self-draft (every draft token "
        "accepted — a throughput ceiling probe, not a speedup). Ignored "
        "when DecodeConfig.spec_draft_serial loads a registry serial")
declare("PADDLE_SERVE_SPEC_MIN_ACCEPT", "float", 0.3, "serving",
        "Adaptive-fallback floor: rolling draft-acceptance rate below "
        "this over a full PADDLE_SERVE_SPEC_WINDOW of spec ticks drops "
        "the engine to plain one-token ticks (specdec.fallback event), "
        "re-arming after a cooldown of the same length")
declare("PADDLE_SERVE_SPEC_WINDOW", "int", 32, "serving",
        "Spec-tick window for the rolling acceptance-rate gauge and the "
        "adaptive controller (also the fallback cooldown length, in "
        "plain ticks)")

# -- serving fleet (router over N engine replicas; serving/fleet.py) --
declare("PADDLE_ROUTER_MAX_REPLICAS", "int", 4, "router",
        "Autoscale ceiling: replicas per model the scale-out policy may "
        "reach (also bounded by the fleet's device pool)")
declare("PADDLE_ROUTER_MIN_REPLICAS", "int", 1, "router",
        "Autoscale floor: scale-in never drops a model below this")
declare("PADDLE_ROUTER_COOLDOWN_S", "float", 5.0, "router",
        "Seconds between scale/drain actions on one model (hysteresis: "
        "a fresh replica must prove itself before the next decision)")
declare("PADDLE_ROUTER_QUEUE_HIGH", "int", 8, "router",
        "Per-model router-queue depth above which sustained pressure "
        "reads as overload (scale-out watermark)")
declare("PADDLE_ROUTER_QUEUE_LOW", "int", 1, "router",
        "Per-model router-queue depth below which sustained idleness "
        "reads as overprovisioning (scale-in watermark)")
declare("PADDLE_ROUTER_QUEUE_HARD", "int", 64, "router",
        "Per-model router-queue hard cap: submits beyond it shed with "
        "EngineOverloaded — but only AFTER the scale policy has had its "
        "chance (a poked scale-out admits the overflow while warming)")
declare("PADDLE_ROUTER_HYSTERESIS_TICKS", "int", 2, "router",
        "Consecutive policy evaluations a watermark must hold before "
        "the decision fires (debounces arrival bursts)")
declare("PADDLE_ROUTER_EVAL_S", "float", 0.25, "router",
        "Autoscale policy evaluation interval (seconds)")
declare("PADDLE_ROUTER_STRAGGLER_FACTOR", "float", 3.0, "router",
        "Drain-and-replace a replica whose median inter-token latency "
        "exceeds factor x the median of its peers (leave-one-out)")
declare("PADDLE_ROUTER_CANARY_FRACTION", "float", 0.125, "router",
        "Fraction of a model's traffic routed to its canary replica "
        "while a new serial is on probation (the fleet-level x% canary)")
declare("PADDLE_ROUTER_HB_TIMEOUT_S", "float", 2.0, "router",
        "Replica heartbeat staleness beyond which the pool census "
        "declares the replica dead and re-spawns it")

# -- fault injection (PADDLE_FAULT_* family; deterministic test faults) --
declare("PADDLE_FAULT_", "prefix", None, "fault",
        "Family prefix: any PADDLE_FAULT_* key is part of the injection "
        "contract parsed by fluid.fault.FaultPlan.from_env")
declare("PADDLE_FAULT_KILL_STEP", "int", None, "fault",
        "Kill this process at training step N")
declare("PADDLE_FAULT_MODE", "str", "exit", "fault",
        "Crash flavor: hard process exit (default) or an in-process "
        "InjectedFault raise (exit|raise)")
declare("PADDLE_FAULT_RANK", "int", None, "fault",
        "Restrict armed faults to one trainer rank")
declare("PADDLE_FAULT_CKPT_CRASH", "str", None, "fault",
        "Crash inside checkpoint save (before|after the _SUCCESS commit)")
declare("PADDLE_FAULT_IO_DELAY_MS", "float", 0.0, "fault",
        "Inject IO delay into reader/prefetch paths (ms)")
declare("PADDLE_FAULT_NAN_VAR", "str", None, "fault",
        "Corrupt this state var with NaNs after a step")
declare("PADDLE_FAULT_NAN_STEP", "int", 0, "fault",
        "Step at which the NaN corruption fires")
declare("PADDLE_FAULT_GRAD_INF_STEP", "int", None, "fault",
        "Poison the backward seed with Inf at step N (in-graph)")
declare("PADDLE_FAULT_GRAD_INF_VALUE", "float", float("inf"), "fault",
        "Poison value for the grad-Inf injection")
declare("PADDLE_FAULT_LOSS_SPIKE_STEP", "int", None, "fault",
        "Multiply the observed loss at step N (spike injection)")
declare("PADDLE_FAULT_LOSS_SPIKE_FACTOR", "float", 1e4, "fault",
        "Spike multiplication factor")
declare("PADDLE_FAULT_BARRIER_STALL", "float", 0.0, "fault",
        "Stall this rank's barrier entry (seconds)")
declare("PADDLE_FAULT_SERVE_DELAY_MS", "float", 0.0, "fault",
        "Per-request serving delay injection (ms)")
declare("PADDLE_FAULT_SERVE_FAIL_EVERY", "int", 0, "fault",
        "Fail every Nth serving request with InjectedFault")
declare("PADDLE_FAULT_DECODE_STALL_MS", "float", 0.0, "fault",
        "Stall every continuous-batching decode tick (ms): deterministic "
        "inter-token-latency inflation, the serving.intertoken_s SLO "
        "breach oracle")
declare("PADDLE_FAULT_CKPT_POISON_SERIAL", "int", None, "fault",
        "NaN-poison checkpoint serial n at save time, committed WITH a "
        "valid _SUCCESS — the structurally-healthy bad checkpoint only "
        "the serving canary catches (hot-swap rollback oracle)")
declare("PADDLE_FAULT_CACHE_CORRUPT", "bool", False, "fault",
        "Deterministically corrupt the next compile-cache read")
declare("PADDLE_FAULT_DATA_STALL_MS", "float", 0.0, "fault",
        "Stall the input pipeline per pulled sample (ms)")
declare("PADDLE_FAULT_DATA_STALL_AT", "int", None, "fault",
        "Fire the data stall once, at this source sample cursor")
declare("PADDLE_FAULT_SHARD_CORRUPT", "bool", False, "fault",
        "Truncate the next data_state blob write (one-shot)")
declare("PADDLE_FAULT_MEM_PRESSURE", "float", 0.0, "fault",
        "Synthesize a memory leak: after PADDLE_FAULT_MEM_PRESSURE_AT "
        "ledger observations, add this many MB of phantom live bytes, "
        "doubling per observation (deterministic memory.live_bytes "
        "breach / budget-overrun oracle)")
declare("PADDLE_FAULT_MEM_PRESSURE_AT", "int", 8, "fault",
        "Ledger observation count at which the synthetic leak starts "
        "(past the SLO watchdog's min-samples baseline)")
declare("PADDLE_FAULT_STRAGGLER_RANK", "int", None, "fault",
        "Deterministic straggler oracle: slow down exactly this trainer "
        "rank (ignores PADDLE_FAULT_RANK — the two faults may target "
        "different ranks in one scenario)")
declare("PADDLE_FAULT_STRAGGLER_MS", "float", 0.0, "fault",
        "Per-step delay (ms) injected into the straggler rank's step "
        "boundary — inflates its window spans so the skew detector "
        "must flag it")
declare("PADDLE_FAULT_HOST_LOSS_RANK", "int", None, "fault",
        "Permanent host loss: this rank exits hard at the armed step "
        "boundary and drops a host_lost marker the supervisor census "
        "reads — the replacement fleet is SMALLER (mesh-ladder oracle)")
declare("PADDLE_FAULT_HOST_LOSS_AT_STEP", "int", 0, "fault",
        "Training step at which the host-loss fault fires")
declare("PADDLE_FAULT_REPLICA_KILL_AFTER", "int", None, "fault",
        "Serving-fleet replica death: kill the replica that served the "
        "n-th fleet request (one-shot) — the deterministic oracle for "
        "the router's re-spawn + cache-hit re-warm path")
declare("PADDLE_FAULT_IO_ERROR_RATE", "float", 0.0, "fault",
        "Transient-storage oracle: fraction of (path, op) keys whose "
        "FIRST read/write attempt raises OSError (seeded per-path hash; "
        "the retry always succeeds — bounded retry must recover, an "
        "unretried call site sees a hard failure)")
declare("PADDLE_FAULT_IO_ERROR_SEED", "int", 0, "fault",
        "Seed for the transient-I/O oracle's per-path failure hash")
declare("PADDLE_FAULT_KV_PAGE_LEAK", "int", None, "fault",
        "Paged-KV leak oracle: the page-pool allocator SKIPS the next n "
        "frees (one-shot), so kvpool.pages_free never returns to its "
        "initial level and the live-buffer ledger / SLO watchdog must "
        "surface the leak deterministically")
declare("PADDLE_FAULT_SPEC_DRAFT_POISON", "int", None, "fault",
        "Speculative-draft poison oracle: from engine tick n on, every "
        "drafted token is replaced with deterministic garbage, so "
        "acceptance collapses to ~1/vocab — the adaptive controller "
        "must fire specdec.fallback while emitted output stays bitwise "
        "correct (corrections are always the target argmax)")

# -- chaos engine (seeded multi-fault drills; paddle_tpu.chaos) --
declare("PADDLE_CHAOS_SEED", "int", None, "chaos",
        "Seed for the chaos schedule's deterministic K-fault plan "
        "sampling (python -m paddle_tpu.chaos run; CLI --seed overrides)")

# -- transient-I/O retry (fluid.retry, wraps durable-state read/write) --
declare("PADDLE_IO_RETRIES", "int", 3, "io",
        "Bounded attempts for transient OSErrors on checkpoint, census "
        "and manifest I/O (1 = no retry; corruption is never retried)")
declare("PADDLE_IO_RETRY_BASE_S", "float", 0.05, "io",
        "Base backoff delay between transient-I/O retries (seconds, "
        "doubling per attempt, capped at 2 s)")

# -- memory observability --
declare("PADDLE_MEM_BUDGET_MB", "float", None, "memory",
        "Per-device HBM budget: the AN502 pre-flight verifier pass and "
        "the live-buffer ledger diagnose programs/residency exceeding it")
declare("PADDLE_MEM_WATERMARK", "bool", True, "memory",
        "Emit memory.watermark run events (live/high-water bytes) at "
        "window boundaries (0 keeps the gauges but silences the events)")

# -- data plane --
declare("PADDLE_DATA_CKPT", "bool", True, "data",
        "Commit/restore checkpointable-reader state with checkpoints "
        "(0 falls back to legacy sample-skip replay)")
declare("PADDLE_DATA_STALL_EVENT_MS", "float", 100.0, "data",
        "Input waits above this emit a data.stall run event")


# ---------------------------------------------------------------------------
# docs/ENV.md generation
# ---------------------------------------------------------------------------


def _fmt_default(knob: EnvKnob) -> str:
    d = knob.default
    if d is None:
        return "unset"
    if isinstance(d, bool):
        return "1" if d else "0"
    if isinstance(d, float) and d == float("inf"):
        return "inf"
    return str(d)


def generate_markdown() -> str:
    lines = [
        "# Environment contract",
        "",
        "Every `PADDLE_*` knob the runtime reads, by subsystem.  GENERATED",
        "by `python -m paddle_tpu.fluid.envcontract > docs/ENV.md` from the",
        "declarations in `paddle_tpu/fluid/envcontract.py` — edit those,",
        "not this file (tier-1 `tools/repo_lint.py` diffs the two, and also",
        "fails on any `os.environ` read of an undeclared `PADDLE_*` key).",
        "",
    ]
    by_sub: Dict[str, List[EnvKnob]] = {}
    for k in knobs():
        by_sub.setdefault(k.subsystem, []).append(k)
    for sub in sorted(by_sub):
        lines.append(f"## {sub}")
        lines.append("")
        lines.append("| knob | type | default | description |")
        lines.append("|---|---|---|---|")
        for k in by_sub[sub]:
            typ = k.type if k.type != "enum" \
                else "enum(" + "|".join(k.choices) + ")"
            name = k.name + "*" if k.type == "prefix" else k.name
            lines.append(f"| `{name}` | {typ} | {_fmt_default(k)} "
                         f"| {k.help} |")
        lines.append("")
    return "\n".join(lines) + ""


if __name__ == "__main__":  # pragma: no cover - exercised via repo_lint
    print(generate_markdown())
