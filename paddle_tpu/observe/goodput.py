"""Goodput accounting + cross-rank straggler attribution (ISSUE 13).

The observability stack built in ISSUEs 5/9/11 emits every raw timing a
fleet operator could want — executor window spans, ``compile_seconds``,
``data.wait_ms``, ``checkpoint.commit``, elastic generation boundaries —
but nothing answers the two questions an autoscaler or elastic-resharding
policy actually asks:

 1. **How much of the wall-clock trained?**  Every second of a run is
    classified into one of the :data:`STATES` — device compute, compile,
    data wait, checkpoint commit, barrier/collective wait,
    restart/re-warm gap, idle/unknown — and
    ``goodput.fraction = device_seconds / wall_seconds`` is the headline
    number (ROADMAP items 1 and 4 consume it: a fleet whose goodput
    craters on every preemption needs resharding, not more replicas).
 2. **Which rank drags the fleet?**  Per-rank step times (the
    ``executor.window`` spans every rank already emits) are compared with
    a leave-one-out median+MAD skew test (:func:`fleet.rank_skew`) and a
    flagged rank lands in the run-event stream as a
    ``straggler.detected{rank=}`` record next to the watchdog's
    ``slo.breach`` events.

Two halves, same state taxonomy:

**Live accumulator** (:class:`GoodputAccumulator`, armed by
``PADDLE_GOODPUT``, default on): the executor/trainer/multihost/data hook
points call :func:`note` with measured seconds; the accumulator keeps
per-state totals, publishes the always-on ``goodput.seconds{state=}``
counters and the ``goodput.fraction{mesh=}`` gauge, and emits one
``goodput.report`` run event every ``PADDLE_GOODPUT_REPORT_S`` seconds.
Stall states additionally feed the SLO watchdog (``goodput.stall_s``) so
a sustained stall regression breaches like a slow step.

**Offline ledger** (:func:`build_ledger`): re-derives the same breakdown
from the PERSISTED event stream alone — no re-run, no live process — by
sweeping the classified span intervals per (host, rank): ``executor.window``
spans are device time, ``executor.trace``/``executor.compile`` spans and
compile-flagged dispatches are compile time, ``checkpoint.save`` /
``barrier.wait`` / ``data.stall`` records are their states, and the gap
between one elastic generation's last activity and the next generation's
first is the restart/re-warm cost of that preemption (priced in lost
steps via the heartbeat ``commit_step`` the incidents carry).  Overlaps
resolve by priority (compile > barrier > data wait > checkpoint > device
> restart) so an async checkpoint writing under a running window counts
as device compute, and every rank's states sum to its wall-clock
exactly.  ``python -m paddle_tpu.observe goodput`` prints it; the
chrome-trace export draws it as a per-rank state track.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "STATES", "GoodputAccumulator", "get_accumulator", "note", "report",
    "build_ledger", "classify_intervals", "reset",
]

#: the wall-clock taxonomy.  "idle" is never noted explicitly — it is
#: whatever the other states do not claim.
STATES = ("device", "compile", "data_wait", "checkpoint", "barrier",
          "restart", "idle")

#: sweep priority for overlapping intervals (higher wins).  device beats
#: checkpoint so a BACKGROUND checkpoint writer under a running window
#: stays productive time; compile beats device because the sharded
#: runner's AOT compile happens inside its window region.
_PRIORITY = {"compile": 6, "barrier": 5, "data_wait": 4, "device": 3,
             "checkpoint": 2, "restart": 1}

#: run-event kinds that map 1:1 onto a state interval ``[ts-dur_s, ts]``
_SPAN_STATES = {
    "executor.window": "device",
    "executor.trace": "compile",
    "executor.compile": "compile",
    "checkpoint.save": "checkpoint",
    "barrier.wait": "barrier",
}

#: states whose live seconds also feed the SLO watchdog as
#: ``goodput.stall_s`` (sustained growth breaches like a slow step)
_STALL_STATES = ("data_wait", "barrier", "checkpoint")


def _ec_get(name: str):
    from ..fluid import envcontract

    return envcontract.get(name)


# ---------------------------------------------------------------------------
# live accumulator
# ---------------------------------------------------------------------------


class GoodputAccumulator:
    """Per-process wall-clock state totals, fed by the runtime hook points.

    ``t0`` anchors the wall-clock denominator; the module anchors it at
    observe import (close to process start) so restart re-warm — imports,
    jax init, checkpoint restore — is visible: on the FIRST device note of
    an elastic generation > 0, the un-attributed time since ``t0`` is
    booked as ``restart`` (generation 0's equivalent stays idle/unknown —
    a cold start is not a restart)."""

    def __init__(self, report_s: Optional[float] = None,
                 t0: Optional[float] = None, gen: Optional[int] = None):
        import os

        self._lock = threading.Lock()
        self.t0 = float(t0 if t0 is not None else _ANCHOR_WALL)
        self.report_s = float(report_s if report_s is not None
                              else _ec_get("PADDLE_GOODPUT_REPORT_S"))
        self.gen = int(gen if gen is not None
                       else os.environ.get("PADDLE_ELASTIC_GENERATION",
                                           "0") or 0)
        self.seconds: Dict[str, float] = {s: 0.0 for s in STATES
                                          if s != "idle"}
        self._last_report = time.time()
        self._rewarm_booked = False

    # -- feeding --
    def note(self, state: str, seconds: float,
             mesh: Optional[str] = None) -> None:
        """Attribute ``seconds`` of wall-clock to ``state`` and refresh the
        published counters/gauges.  Never raises."""
        if state not in self.seconds:
            return
        seconds = max(0.0, float(seconds))
        with self._lock:
            if state == "device" and not self._rewarm_booked:
                self._rewarm_booked = True
                if self.gen > 0:
                    # everything before the first device window of a
                    # RESTARTED generation that no other state claimed is
                    # re-warm cost (imports, jax init, checkpoint load)
                    pre = (time.time() - seconds) - self.t0 \
                        - sum(self.seconds.values())
                    if pre > 0.0:
                        self.seconds["restart"] += pre
                        self._publish("restart", pre, None)
            self.seconds[state] += seconds
            fraction = self.fraction_locked()
        self._publish(state, seconds, mesh, fraction=fraction)
        if state in _STALL_STATES:
            try:
                from . import watchdog

                watchdog.observe_value("goodput.stall_s", seconds,
                                       state=state)
            except Exception:
                pass
        self.maybe_report(mesh=mesh)

    def _publish(self, state: str, seconds: float, mesh: Optional[str],
                 fraction: Optional[float] = None) -> None:
        try:
            from . import registry

            reg = registry()
            reg.inc("goodput.seconds", seconds, labels={"state": state})
            if fraction is not None:
                reg.set_gauge("goodput.fraction", round(fraction, 6))
                if mesh:
                    reg.set_gauge("goodput.fraction", round(fraction, 6),
                                  labels={"mesh": mesh})
        except Exception:
            pass  # accounting must never fail the run it measures

    # -- reading --
    def elapsed(self) -> float:
        return max(1e-9, time.time() - self.t0)

    def fraction_locked(self) -> float:
        return min(1.0, self.seconds["device"] / self.elapsed())

    def fraction(self) -> float:
        with self._lock:
            return self.fraction_locked()

    def snapshot(self) -> dict:
        with self._lock:
            states = dict(self.seconds)
            elapsed = self.elapsed()
        states["idle"] = max(0.0, elapsed - sum(states.values()))
        return {"elapsed_s": round(elapsed, 6),
                "states": {k: round(v, 6) for k, v in states.items()},
                "fraction": round(min(1.0, states["device"] / elapsed), 6),
                "gen": self.gen}

    def maybe_report(self, mesh: Optional[str] = None,
                     force: bool = False) -> Optional[dict]:
        """Emit one ``goodput.report`` run event when the report interval
        elapsed (or ``force``); returns the report payload when emitted."""
        now = time.time()
        with self._lock:
            if not force and now - self._last_report < self.report_s:
                return None
            self._last_report = now
        snap = self.snapshot()
        try:
            from . import emit

            emit("goodput.report", mesh=mesh, **snap)
        except Exception:
            pass
        return snap


# anchored at module import (observe imports goodput at package import, so
# this is within milliseconds of the first paddle_tpu import — close
# enough to process start for re-warm attribution)
_ANCHOR_WALL = time.time()

# late-binding singleton (the watchdog/_UNSET contract: a subprocess that
# sets PADDLE_GOODPUT before first use is honored)
_UNSET = object()
_acc = _UNSET
_acc_lock = threading.Lock()


def get_accumulator() -> Optional[GoodputAccumulator]:
    """The process accumulator, or None when ``PADDLE_GOODPUT=0``."""
    global _acc
    if _acc is _UNSET:
        with _acc_lock:
            if _acc is _UNSET:
                try:
                    _acc = GoodputAccumulator() \
                        if _ec_get("PADDLE_GOODPUT") else None
                except Exception:
                    _acc = None
    return _acc


def note(state: str, seconds: float, mesh: Optional[str] = None) -> None:
    """Feed the process accumulator; no-op when disarmed.  Never raises."""
    try:
        acc = get_accumulator()
        if acc is not None:
            acc.note(state, seconds, mesh=mesh)
    except Exception:
        pass


def report(force: bool = True) -> Optional[dict]:
    """Emit a ``goodput.report`` now (the trainer's end-of-run flush and
    the smoke tool call this); None when disarmed."""
    acc = get_accumulator()
    if acc is None:
        return None
    return acc.maybe_report(force=force)


def reset() -> None:
    """Drop the singleton and re-arm env late-binding (test hook, called
    from ``observe.reset``)."""
    global _acc
    with _acc_lock:
        _acc = _UNSET


# ---------------------------------------------------------------------------
# offline ledger: persisted event stream -> per-rank state breakdown
# ---------------------------------------------------------------------------


def _record_interval(r: dict) -> Optional[Tuple[float, float, str]]:
    """(start, end, state) for one run-event record, or None."""
    ev = r.get("event")
    state = _SPAN_STATES.get(ev)
    if state is not None:
        dur = r.get("dur_s")
        if dur is None:
            return None
        ts = float(r.get("ts", 0.0))
        return ts - float(dur), ts, state
    if ev == "executor.dispatch" and r.get("compile"):
        # the single-device path compiles lazily inside its first
        # dispatch; that dispatch is compile cost, not steady-state
        dur = r.get("dur_s")
        if dur is None:
            return None
        ts = float(r.get("ts", 0.0))
        return ts - float(dur), ts, "compile"
    if ev == "data.stall":
        wait_ms = r.get("wait_ms")
        if wait_ms is None:
            return None
        ts = float(r.get("ts", 0.0))
        return ts - float(wait_ms) / 1e3, ts, "data_wait"
    return None


def classify_intervals(records: List[dict]) -> Dict[str, dict]:
    """Group the merged stream per worker ``host:r<rank>``: classified
    state intervals plus per-generation activity bounds (restart gaps are
    derived from the latter).  Supervisor-sourced records are excluded
    from per-rank timelines (they are not worker wall-clock)."""
    per: Dict[str, dict] = {}
    for r in records:
        if r.get("source") == "supervisor":
            continue
        key = f"{r.get('host', '?')}:r{r.get('rank', 0)}"
        w = per.setdefault(key, {"intervals": [], "gens": {},
                                 "host": r.get("host", "?"),
                                 "rank": int(r.get("rank", 0) or 0)})
        iv = _record_interval(r)
        ts = float(r.get("ts", 0.0))
        lo = iv[0] if iv is not None else ts
        gen = int(r.get("gen", 0) or 0)
        bounds = w["gens"].get(gen)
        if bounds is None:
            w["gens"][gen] = [lo, ts]
        else:
            bounds[0] = min(bounds[0], lo)
            bounds[1] = max(bounds[1], ts)
        if iv is not None:
            w["intervals"].append(iv)
    # restart gaps: between consecutive generations' activity, per rank
    for w in per.values():
        gens = sorted(w["gens"])
        for a, b in zip(gens, gens[1:]):
            end_prev, start_next = w["gens"][a][1], w["gens"][b][0]
            if start_next > end_prev:
                w["intervals"].append((end_prev, start_next, "restart"))
    return per


def _sweep(intervals: List[Tuple[float, float, str]], t0: float,
           t1: float) -> Tuple[Dict[str, float], List[dict]]:
    """Priority sweep of ``[t0, t1]``: per-state seconds (always summing
    to exactly ``t1 - t0``, unclaimed time is idle) plus the swept
    non-idle segments (the chrome state track)."""
    seconds = {s: 0.0 for s in STATES}
    segments: List[dict] = []
    ivs = [(max(t0, s), min(t1, e), st) for s, e, st in intervals
           if e > t0 and s < t1 and e > s]
    pts = sorted({t0, t1, *(p for s, e, _ in ivs for p in (s, e))})
    for a, b in zip(pts, pts[1:]):
        if b <= a:
            continue
        state = "idle"
        prio = 0
        for s, e, st in ivs:
            if s < b and e > a and _PRIORITY.get(st, 0) > prio:
                state, prio = st, _PRIORITY[st]
        seconds[state] += b - a
        if state != "idle":
            if segments and segments[-1]["state"] == state \
                    and abs(segments[-1]["t1"] - a) < 1e-9:
                segments[-1]["t1"] = b
            else:
                segments.append({"state": state, "t0": a, "t1": b})
    return seconds, segments


def _restart_pricing(records: List[dict], per: Dict[str, dict]) -> List[dict]:
    """One entry per (rank, generation gap), priced in lost steps where a
    worker_exit/heartbeat_timeout incident carries progress-at-death
    (``last_step`` vs heartbeat ``commit_step`` — ISSUE 13 satellite).
    A supervisor ``mesh.downgrade`` incident covering the gap's target
    generation additionally prices the TOPOLOGY transition
    (``mesh_from``/``mesh_to``/``nproc_from``/``nproc_to`` — ISSUE 14):
    a restart that also shrank the mesh is a different cost class from a
    same-size relaunch, and the ledger is where an autoscaler reads
    that."""
    deaths: Dict[Tuple[int, int], dict] = {}
    downgrades: Dict[int, dict] = {}
    for r in records:
        if r.get("event") in ("worker_exit", "heartbeat_timeout"):
            g = r.get("generation")
            rk = r.get("rank")
            if g is not None and rk is not None:
                deaths[(int(g), int(rk))] = r
        elif r.get("event") == "mesh.downgrade":
            g = r.get("generation")
            if g is not None:
                downgrades[int(g)] = r
    out: List[dict] = []
    for key, w in sorted(per.items()):
        gens = sorted(w["gens"])
        for a, b in zip(gens, gens[1:]):
            gap = w["gens"][b][0] - w["gens"][a][1]
            entry = {"worker": key, "rank": w["rank"], "from_gen": a,
                     "to_gen": b, "gap_s": round(max(0.0, gap), 6)}
            death = deaths.get((a, w["rank"]))
            if death is not None:
                last = death.get("last_step")
                commit = death.get("commit_step")
                entry["last_step"] = last
                entry["commit_step"] = commit
                if isinstance(last, int) and isinstance(commit, int):
                    entry["lost_steps"] = max(0, last - commit)
            down = downgrades.get(b)
            if down is not None:
                entry["mesh_from"] = down.get("from_mesh")
                entry["mesh_to"] = down.get("to_mesh")
                entry["nproc_from"] = down.get("from_nproc")
                entry["nproc_to"] = down.get("to_nproc")
            out.append(entry)
    return out


def build_ledger(records: List[dict]) -> dict:
    """The whole-run goodput ledger from a merged event stream (the
    ``observe goodput`` CLI's payload; needs no live process).

    Per worker: state seconds summing exactly to its wall window
    (first-to-last activity) and the swept state segments.  Fleet level:
    summed state seconds, ``fraction = device / total``, the restart list
    with lost-work pricing, and the straggler events already persisted in
    the stream."""
    per = classify_intervals(records)
    ranks: Dict[str, dict] = {}
    fleet = {s: 0.0 for s in STATES}
    segments: List[dict] = []
    total = 0.0
    for key, w in sorted(per.items()):
        t0 = min(b[0] for b in w["gens"].values())
        t1 = max(b[1] for b in w["gens"].values())
        seconds, segs = _sweep(w["intervals"], t0, t1)
        wall = t1 - t0
        for s, v in seconds.items():
            fleet[s] += v
        total += wall
        for seg in segs:
            seg.update(worker=key, host=w["host"], rank=w["rank"])
        segments.extend(segs)
        ranks[key] = {
            "t0": t0, "t1": t1, "wall_s": round(wall, 6),
            "states": {s: round(v, 6) for s, v in seconds.items()},
            "coverage": round(sum(seconds.values()) / wall, 6)
            if wall > 0 else 1.0,
            "generations": sorted(w["gens"]),
        }
    stragglers = [r for r in records
                  if r.get("event") == "straggler.detected"]
    return {
        "workers": sorted(ranks),
        "ranks": ranks,
        "states": {s: round(v, 6) for s, v in fleet.items()},
        "total_s": round(total, 6),
        "fraction": round(fleet["device"] / total, 6) if total > 0 else 0.0,
        "restarts": _restart_pricing(records, per),
        "straggler_events": [
            {k: r.get(k) for k in ("ts", "rank", "host", "generation",
                                   "median_step_s", "baseline_step_s",
                                   "ratio")}
            for r in stragglers],
        "segments": segments,
    }
