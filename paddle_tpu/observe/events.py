"""Run-event log: append-only JSONL, stamped for cross-process correlation.

Every record carries the full correlation key the fleet aggregator joins
on — ``(host, rank, gen, step)`` plus the executing program's fingerprint
— so a guardian trip in generation 0, the compile-cache hits that made
generation 1's restart cheap, and the supervisor's ``generation_start``
decision all line up in ONE stream ordered by wall clock:

    {"ts": 1722777601.22, "event": "guardian_trip", "host": "tpu-a",
     "pid": 911, "rank": 0, "gen": 0, "step": 2, "program": "a31f09e2c4d1",
     "policy": "halt", "loss": Infinity, ...}

Writes are one ``write()`` of one line on a file opened in append mode
under a lock — atomic enough for many threads in one process; cross-process
writers use DISTINCT files (one per (host, rank, generation), see
``observe.Sink``) that the aggregator merges by timestamp, so there is no
shared-file interleaving to get wrong.

Schema contract (docs/OBSERVABILITY.md): ``ts`` (unix seconds), ``event``
(dot-separated kind), the stamp fields above, then free-form JSON fields.
``dur_s`` marks a span (emitted at close by :meth:`EventLog.span`).
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from typing import Iterable, List, Optional

__all__ = ["EventLog", "read_events", "merge_events", "host_name"]


def host_name() -> str:
    try:
        return socket.gethostname() or "localhost"
    except OSError:
        return "localhost"


def _env_int(name: str, default: int = 0) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class EventLog:
    """One append-only JSONL event stream.

    ``host``/``rank``/``gen`` default from the standard pod env
    (``PADDLE_TRAINER_ID`` / ``PADDLE_ELASTIC_GENERATION``) read at
    construction; ``step``/``program`` are read per-event from the
    process-wide context (``observe.note_step`` / ``note_program``) so the
    executor's hot path stamps events without threading arguments through
    every subsystem."""

    def __init__(self, path: str, *, host: Optional[str] = None,
                 rank: Optional[int] = None, gen: Optional[int] = None,
                 source: Optional[str] = None):
        self.path = os.path.abspath(path)
        self.host = host if host is not None else host_name()
        self.rank = rank if rank is not None \
            else _env_int("PADDLE_TRAINER_ID")
        self.gen = gen if gen is not None \
            else _env_int("PADDLE_ELASTIC_GENERATION")
        self.source = source
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)

    def emit(self, event: str, **fields) -> dict:
        """Append one stamped record; returns it.  Never raises — losing a
        telemetry line must not fail the run it describes."""
        from . import current_mesh, current_program, current_step

        rec = {"ts": time.time(), "event": event, "host": self.host,
               "pid": os.getpid(), "rank": self.rank, "gen": self.gen,
               "step": current_step(), "program": current_program()}
        mesh = current_mesh()
        if mesh is not None:
            # topology stamp (dp4xtp2) — only present on sharded runs, so
            # single-device streams keep their exact record shape
            rec["mesh"] = mesh
        try:
            from . import trace as _trace

            sp = _trace.current()
            if sp is not None:
                # trace stamp: any record emitted inside an open span
                # (guardian trips, cache probes, slo breaches) joins the
                # span tree.  Span records override via `fields` below.
                rec["trace_id"] = sp.trace_id
                rec["span_id"] = sp.span_id
        except Exception:
            pass
        if self.source:
            rec["source"] = self.source
        rec.update(fields)
        try:
            line = json.dumps(rec, default=repr) + "\n"
            with self._lock, open(self.path, "a") as f:
                f.write(line)
        except (OSError, ValueError):
            pass
        return rec

    @contextlib.contextmanager
    def span(self, event: str, **fields):
        """Timed region: emits one record with ``dur_s`` when it closes."""
        t = time.perf_counter()
        try:
            yield
        finally:
            self.emit(event, dur_s=round(time.perf_counter() - t, 6),
                      **fields)


def read_events(path: str) -> List[dict]:
    """Parse one JSONL event file, skipping torn/corrupt lines."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def merge_events(paths: Iterable[str]) -> List[dict]:
    """All records from ``paths`` in one wall-clock-ordered stream."""
    recs = []
    for p in paths:
        recs.extend(read_events(p))
    recs.sort(key=lambda r: r.get("ts", 0))
    return recs
