"""Exporters: Prometheus text, JSON snapshot files, chrome-trace merge.

Prometheus exposition is the lingua franca of fleet scrapers; the renderer
here is intentionally parseable by its own :func:`parse_prometheus_text`
so the round trip (registry -> text -> parse -> same values) is a CI
oracle, not a hope.  Metric names sanitize ``.`` and other non-identifier
characters to ``_`` (``compile_cache.hit`` -> ``compile_cache_hit``);
labels pass through in ``name{k="v"}`` form.

The chrome-trace exporter turns merged run-event logs into a
``chrome://tracing`` / perfetto file: spans (records with ``dur_s``)
become ``"ph": "X"`` duration events, other records become ``"ph": "i"``
instants, counter samples become ``"ph": "C"`` counter tracks, and every
(host, rank) pair gets its own pid with a ``process_name`` metadata row —
one timeline for the whole fleet.  The jax device trace stays in its
``trace_dir`` (xplane protobuf, opened by TensorBoard/perfetto natively);
the exporter records the pointer in the trace metadata rather than
pretending to transcode it.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Iterable, List, Optional

from .registry import split_name

__all__ = ["sanitize_metric_name", "prometheus_text",
           "parse_prometheus_text", "write_snapshot", "chrome_trace"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _render_line(rendered_key: str, value, out: List[str],
                 suffix: str = "", extra_label: str = "") -> None:
    name, labels = split_name(rendered_key)
    name = sanitize_metric_name(name) + suffix
    items = [f'{k}="{v}"' for k, v in labels]
    if extra_label:
        items.append(extra_label)
    label_s = "{" + ",".join(items) + "}" if items else ""
    out.append(f"{name}{label_s} {value}")


def prometheus_text(snapshot: dict) -> str:
    """Render a ``MetricsRegistry.snapshot()``-shaped dict (or a flat
    name->value dict, treated as gauges) to Prometheus exposition text."""
    if "counters" not in snapshot and "gauges" not in snapshot:
        snapshot = {"counters": {}, "gauges": dict(snapshot),
                    "histograms": {}}
    lines: List[str] = []
    for key in sorted(snapshot.get("counters", {})):
        name, _ = split_name(key)
        lines.append(f"# TYPE {sanitize_metric_name(name)} counter")
        _render_line(key, snapshot["counters"][key], lines)
    for key in sorted(snapshot.get("gauges", {})):
        v = snapshot["gauges"][key]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue  # only numeric gauges are exposable
        name, _ = split_name(key)
        lines.append(f"# TYPE {sanitize_metric_name(name)} gauge")
        _render_line(key, v, lines)
    for key in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][key]
        name, _ = split_name(key)
        lines.append(f"# TYPE {sanitize_metric_name(name)} histogram")
        cum = 0
        for ub, c in zip(h["buckets"], h["counts"]):
            cum += c
            _render_line(key, cum, lines, suffix="_bucket",
                         extra_label=f'le="{ub}"')
        cum += h["counts"][-1] if len(h["counts"]) > len(h["buckets"]) \
            else 0
        _render_line(key, cum, lines, suffix="_bucket",
                     extra_label='le="+Inf"')
        _render_line(key, h["sum"], lines, suffix="_sum")
        _render_line(key, h["count"], lines, suffix="_count")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Parse exposition text back into ``{"counters": {...}, "gauges":
    {...}, "histograms": {name: {"sum":, "count":}}}`` keyed on the
    SANITIZED rendered names (the round-trip oracle's comparison form)."""
    types: Dict[str, str] = {}
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            mname, _, mtype = rest.partition(" ")
            types[mname] = mtype.strip()
            continue
        if line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z0-9_:]+)(\{[^}]*\})?\s+(\S+)$", line)
        if not m:
            continue
        name, labels, val = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(val)
        except ValueError:
            continue
        if value == int(value):
            value = int(value)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and types.get(name[:-len(suffix)]) == "histogram":
                base = name[:-len(suffix)]
                h = out["histograms"].setdefault(base, {})
                if suffix == "_sum":
                    h["sum"] = value
                elif suffix == "_count":
                    h["count"] = value
                break
        else:
            key = name + labels
            if types.get(name) == "counter":
                out["counters"][key] = value
            else:
                out["gauges"][key] = value
    return out


def write_snapshot(dir_path: str, snapshot: dict, *, stem: str,
                   meta: Optional[dict] = None) -> List[str]:
    """Atomically (tmp + rename) write ``<stem>.json`` and ``<stem>.prom``
    under ``dir_path``; returns the paths.  The JSON carries ``meta`` (the
    writer's host/rank/gen stamp) so the fleet aggregator never has to
    parse filenames."""
    os.makedirs(dir_path, exist_ok=True)
    payload = {"meta": meta or {}}
    payload.update(snapshot)
    paths = []
    for ext, data in ((".json", json.dumps(payload)),
                      (".prom", prometheus_text(snapshot))):
        path = os.path.join(dir_path, stem + ext)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(data)
        os.replace(tmp, path)
        paths.append(path)
    return paths


# ---------------------------------------------------------------------------
# chrome trace
# ---------------------------------------------------------------------------


def _pid_table(records: Iterable[dict]) -> Dict[tuple, int]:
    """(host, rank) -> stable pid, in first-seen order."""
    pids: Dict[tuple, int] = {}
    for r in records:
        key = (r.get("host", "?"), r.get("rank", 0))
        if key not in pids:
            pids[key] = len(pids)
    return pids


#: chrome-trace tid reserved for the per-rank goodput state track (far
#: above any real thread's first-use index)
GOODPUT_TID = 9999


def chrome_trace(records: List[dict],
                 counter_samples: Optional[List[dict]] = None,
                 device_trace_dir: Optional[str] = None,
                 goodput_segments: Optional[List[dict]] = None) -> dict:
    """Merged event records -> chrome://tracing JSON dict.

    ``records`` come from :func:`events.merge_events`; ``counter_samples``
    are the profiler session's (ts, name, value) samples (emitted as
    ``"ph": "C"`` on pid 0).  ``goodput_segments`` (the swept per-rank
    state intervals from :func:`goodput.build_ledger`) render as one
    dedicated "goodput state" thread row per (host, rank) — the
    wall-clock state track drawn under that rank's spans, so a restart
    gap or data stall is visible at a glance."""
    trace_events: List[dict] = []
    pids = _pid_table(records)
    if not pids:
        pids[("host", 0)] = 0
    for (host, rank), pid in pids.items():
        trace_events.append({"name": "process_name", "ph": "M", "pid": pid,
                             "args": {"name": f"{host}:r{rank}"}})
    t0 = min((r.get("ts", 0) for r in records), default=0)
    if goodput_segments:
        seen_pids = set()
        for seg in goodput_segments:
            pid = pids.get((seg.get("host", "?"), seg.get("rank", 0)))
            if pid is None:
                continue
            if pid not in seen_pids:
                seen_pids.add(pid)
                trace_events.append(
                    {"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": GOODPUT_TID,
                     "args": {"name": "goodput state"}})
            ts_us = (seg["t0"] - t0) * 1e6
            trace_events.append(
                {"ph": "X", "cat": "goodput", "ts": ts_us,
                 "dur": max(0.0, (seg["t1"] - seg["t0"]) * 1e6),
                 "pid": pid, "tid": GOODPUT_TID,
                 "name": f"state:{seg.get('state', '?')}",
                 "args": {"state": seg.get("state")}})
    for r in records:
        pid = pids.get((r.get("host", "?"), r.get("rank", 0)), 0)
        ts_us = (r.get("ts", t0) - t0) * 1e6
        args = {k: v for k, v in r.items()
                if k not in ("ts", "event") and v is not None}
        # span records (the trace module stamps an emitting-thread `tid`)
        # keep their own thread row, so a prefetch worker's staging spans
        # never overlap the executor's window spans on one track; legacy
        # records without a tid keep the per-generation rows
        tid = r.get("tid", r.get("gen", 0))
        # records carrying a `counters` dict ({metric name: value} — the
        # memory.watermark events) additionally render as "ph": "C"
        # counter tracks, so HBM residency draws alongside the spans
        counters = r.get("counters")
        if isinstance(counters, dict):
            for cname, cval in sorted(counters.items()):
                if isinstance(cval, (int, float)) \
                        and not isinstance(cval, bool):
                    trace_events.append({"ph": "C", "pid": pid,
                                         "ts": ts_us, "name": str(cname),
                                         "args": {"value": cval}})
        if r.get("dur_s") is not None:
            dur_us = float(r["dur_s"]) * 1e6
            trace_events.append({"ph": "X", "cat": "event",
                                 "ts": ts_us - dur_us, "dur": dur_us,
                                 "pid": pid, "tid": tid,
                                 "name": r.get("event", "?"), "args": args})
        else:
            trace_events.append({"ph": "i", "cat": "event", "ts": ts_us,
                                 "pid": pid, "tid": tid,
                                 "s": "p",
                                 "name": r.get("event", "?"), "args": args})
    for s in counter_samples or []:
        trace_events.append({"ph": "C", "pid": 0, "ts": s["ts"],
                             "name": s["name"],
                             "args": {"value": s["value"]}})
    out = {"traceEvents": trace_events}
    if device_trace_dir:
        out["otherData"] = {"device_trace_dir": device_trace_dir,
                            "note": "open the xplane capture in "
                                    "TensorBoard/perfetto alongside"}
    return out
