"""Fleet aggregation: many (host, rank, generation) files -> one view.

Each worker process writes its own metric snapshot
(``metrics-<host>-r<rank>-g<gen>.json``) and event log
(``events-<host>-r<rank>-g<gen>.jsonl``) under the shared observe dir —
never a shared file, so there is no cross-process interleaving to referee.
The aggregator's job is the join:

 - **per-worker views** keyed ``<host>:r<rank>:g<gen>`` (exactly what each
   process reported, stamp included);
 - **fleet sums**: counters summed over the LATEST generation of each
   (host, rank) — a restarted worker's counters restart from zero, so
   summing every generation would double-count the survivor's history;
   earlier generations remain visible in the per-worker views;
 - **merged events**: every generation's stream, wall-clock ordered (the
   supervisor's restarts, guardian trips, cache hits in one timeline).

This is what ``python -m paddle_tpu.observe summary`` prints and what the
elastic supervisor persists as ``fleet.json`` at the end of a run.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional

from .events import merge_events
from .registry import split_name

__all__ = ["scan_dir", "fleet_snapshot", "fleet_events", "write_fleet",
           "rank_skew", "follow_events", "label_sums"]

METRICS_GLOB = "metrics-*.json"
EVENTS_GLOB = "events-*.jsonl"


def scan_dir(root: str) -> Dict[str, List[str]]:
    root = os.path.abspath(root)
    return {"metrics": sorted(glob.glob(os.path.join(root, METRICS_GLOB))),
            "events": sorted(glob.glob(os.path.join(root, EVENTS_GLOB)))}


def _load_metrics(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None  # torn/corrupt snapshot: skip, never fail the fleet view
    # a torn write can still be VALID json of the wrong shape (e.g. a bare
    # number from a truncated tail) — shape-check here so the aggregation
    # below never AttributeErrors on a non-dict "snapshot"
    if not isinstance(snap, dict) or not isinstance(snap.get("meta", {}),
                                                    dict):
        return None
    return snap


def fleet_snapshot(root: str) -> dict:
    """Aggregate every worker's newest metric snapshot under ``root``.

    Partial-fleet tolerance (ISSUE 11 satellite): a missing, truncated or
    corrupt per-rank snapshot must not take the whole view down — the
    surviving ranks merge, the casualties are listed under ``partial``,
    and one ``fleet.partial`` run event is emitted (into the aggregating
    process's own sink, when it has one) so the degradation is visible in
    the stream instead of silently under-counting the fleet."""
    workers: Dict[str, dict] = {}
    latest: Dict[tuple, dict] = {}  # (host, rank) -> snapshot of max gen
    partial: List[str] = []
    for path in scan_dir(root)["metrics"]:
        snap = _load_metrics(path)
        if snap is None:
            partial.append(os.path.basename(path))
            continue
        meta = snap.get("meta", {})
        host = meta.get("host", os.path.basename(path))
        rank, gen = meta.get("rank", 0), meta.get("gen", 0)
        workers[f"{host}:r{rank}:g{gen}"] = snap
        key = (host, rank)
        if key not in latest or latest[key]["meta"].get("gen", 0) <= gen:
            latest[key] = snap
    summed: Dict[str, float] = {}
    for snap in latest.values():
        for name, v in snap.get("counters", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                summed[name] = summed.get(name, 0) + v
    gauges: Dict[str, dict] = {}
    for key, snap in latest.items():
        label = f"{key[0]}:r{key[1]}"
        for name, v in snap.get("gauges", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauges.setdefault(name, {})[label] = v
    if partial:
        try:
            from . import emit

            emit("fleet.partial", root=os.path.abspath(root),
                 skipped=sorted(partial), survivors=sorted(workers))
        except Exception:
            pass
    return {"ts": time.time(), "root": os.path.abspath(root),
            "workers": sorted(workers),
            "counters_sum": summed,
            "gauges_by_worker": gauges,
            "partial": sorted(partial),
            "per_worker": workers}


def fleet_events(root: str) -> List[dict]:
    """Every worker generation's events, one wall-clock-ordered stream."""
    return merge_events(scan_dir(root)["events"])


def label_sums(counters: Dict[str, float], key: str,
               prefix: str = "") -> Dict[str, Dict[str, float]]:
    """Group a flat counter/gauge dict by one label dimension (ISSUE 17
    satellite): ``label value -> {base metric name -> summed value}``.

    Serving replicas mirror their counters into the process registry
    with ``model=``/``replica=`` labels (``serving.completed{model=
    "chat",replica="chat-r1"}``); this is the structured join the fleet
    view does over them — per-model (``key="model"``) or per-replica
    (``key="replica"``) sums via :func:`~paddle_tpu.observe.registry.
    split_name`, never by string-parsing metric names.  Metrics without
    the label are skipped; remaining labels (e.g. ``replica`` inside a
    per-model sum) are summed over.  ``prefix`` filters base names
    (``"serving."`` for the serving family)."""
    out: Dict[str, Dict[str, float]] = {}
    for rendered, v in counters.items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        name, labels = split_name(rendered)
        if prefix and not name.startswith(prefix):
            continue
        val = dict(labels).get(key)
        if val is None:
            continue
        bucket = out.setdefault(val, {})
        bucket[name] = bucket.get(name, 0) + v
    return out


def _median(vals) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def rank_skew(records: List[dict], *, factor: float = 1.5,
              min_samples: int = 4, gen: Optional[int] = None,
              warmup: int = 2) -> dict:
    """Cross-rank step-time skew over the merged event stream (ISSUE 13).

    Per worker ``host:r<rank>``, per-step times come from the
    ``executor.window`` spans every rank already emits (``dur_s`` /
    ``n_steps``); per-rank BARRIER wait totals ride along for context.
    The straggler test is leave-one-out median+MAD: a rank is flagged
    when its median step time exceeds ``factor`` x the median of the
    OTHER ranks' medians AND clears their 3xMAD noise guard — the
    leave-one-out form keeps a 2-rank fleet decidable (a plain fleet
    median+MAD can never flag one of two ranks: the outlier drags the
    baseline it is judged against).

    Each (worker, generation)'s first ``warmup`` windows and any
    ``fresh``-flagged window (lazy jit compile inside the span) are
    EXCLUDED: warm-up transients are 10-100x steady state, so a freshly
    restarted rank with few samples would otherwise read as a straggler
    of its own recovery.  Needs >= ``min_samples`` STEADY samples on the
    candidate AND at least one other qualified rank; returns per-rank
    stats and the flagged stragglers (empty when the fleet is
    single-rank or too young)."""
    raw: Dict[str, Dict[int, List[tuple]]] = {}
    barrier: Dict[str, float] = {}
    meta: Dict[str, dict] = {}
    for r in records:
        if r.get("source") == "supervisor":
            continue
        if gen is not None and int(r.get("gen", 0) or 0) != gen:
            continue
        key = f"{r.get('host', '?')}:r{r.get('rank', 0)}"
        ev = r.get("event")
        dur = r.get("dur_s")
        if ev == "executor.window" and dur is not None:
            n = max(1, int(r.get("n_steps") or 1))
            g = int(r.get("gen", 0) or 0)
            raw.setdefault(key, {}).setdefault(g, []).append(
                (float(r.get("ts", 0.0)), float(dur) / n,
                 bool(r.get("fresh"))))
            meta.setdefault(key, {"host": r.get("host", "?"),
                                  "rank": int(r.get("rank", 0) or 0)})
        elif ev == "barrier.wait" and dur is not None:
            barrier[key] = barrier.get(key, 0.0) + float(dur)
    steps: Dict[str, List[float]] = {}
    for key, by_gen in raw.items():
        vals: List[float] = []
        for g, samples in by_gen.items():
            samples.sort()
            vals.extend(v for _, v, fresh in samples[warmup:] if not fresh)
        if vals:
            steps[key] = vals
    ranks = {}
    for key, vals in steps.items():
        ranks[key] = {"median_step_s": round(_median(vals), 6),
                      "n": len(vals),
                      "barrier_wait_s": round(barrier.get(key, 0.0), 6),
                      **meta[key]}
    qualified = {k: v for k, v in ranks.items() if v["n"] >= min_samples}
    stragglers = []
    for key, own in qualified.items():
        others = [v["median_step_s"] for k, v in qualified.items()
                  if k != key]
        if not others:
            continue
        baseline = _median(others)
        mad = _median([abs(x - baseline) for x in others])
        if baseline > 0.0 and own["median_step_s"] > baseline * factor \
                and own["median_step_s"] > baseline + 3.0 * mad:
            stragglers.append({
                "worker": key, "host": own["host"], "rank": own["rank"],
                "median_step_s": own["median_step_s"],
                "baseline_step_s": round(baseline, 6),
                "ratio": round(own["median_step_s"] / baseline, 3),
                "n": own["n"]})
    stragglers.sort(key=lambda s: -s["ratio"])
    return {"ranks": ranks, "stragglers": stragglers, "factor": factor,
            "min_samples": min_samples, "gen": gen}


def follow_events(root: str, poll_s: float = 0.5, stop_check=None,
                  from_end: bool = False):
    """Poll-based ``tail -f`` over every event file under ``root``: yields
    new records (wall-clock ordered per poll) as they are appended, and
    picks up files that appear later (a new generation's worker).  Torn
    trailing lines are left in the buffer until their newline lands.
    ``from_end=True`` skips the files' existing content (the CLI prints
    the history itself, then follows only what is NEW; files appearing
    mid-follow still stream from their start).  ``stop_check`` (callable
    -> bool) ends the generator — the CLI's ``--follow`` loop runs until
    interrupted; tests pass a flag."""
    import time as _time

    offsets: Dict[str, int] = {}
    buffers: Dict[str, str] = {}
    if from_end:
        for path in scan_dir(root)["events"]:
            try:
                offsets[path] = os.path.getsize(path)
            except OSError:
                pass
    while stop_check is None or not stop_check():
        batch: List[dict] = []
        for path in scan_dir(root)["events"]:
            try:
                with open(path) as f:
                    f.seek(offsets.get(path, 0))
                    chunk = f.read()
                    offsets[path] = f.tell()
            except OSError:
                continue
            if not chunk:
                continue
            data = buffers.get(path, "") + chunk
            lines = data.split("\n")
            buffers[path] = lines.pop()  # "" when chunk ended on newline
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    batch.append(json.loads(line))
                except ValueError:
                    continue
        batch.sort(key=lambda r: r.get("ts", 0))
        for rec in batch:
            yield rec
        if stop_check is not None and stop_check():
            return
        _time.sleep(max(0.05, float(poll_s)))


def write_fleet(root: str, path: Optional[str] = None) -> Optional[str]:
    """Persist the aggregated snapshot as ``<root>/fleet.json`` (atomic).
    Returns the path, or None when nothing could be written."""
    snap = fleet_snapshot(root)
    path = path or os.path.join(os.path.abspath(root), "fleet.json")
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
