"""Fleet aggregation: many (host, rank, generation) files -> one view.

Each worker process writes its own metric snapshot
(``metrics-<host>-r<rank>-g<gen>.json``) and event log
(``events-<host>-r<rank>-g<gen>.jsonl``) under the shared observe dir —
never a shared file, so there is no cross-process interleaving to referee.
The aggregator's job is the join:

 - **per-worker views** keyed ``<host>:r<rank>:g<gen>`` (exactly what each
   process reported, stamp included);
 - **fleet sums**: counters summed over the LATEST generation of each
   (host, rank) — a restarted worker's counters restart from zero, so
   summing every generation would double-count the survivor's history;
   earlier generations remain visible in the per-worker views;
 - **merged events**: every generation's stream, wall-clock ordered (the
   supervisor's restarts, guardian trips, cache hits in one timeline).

This is what ``python -m paddle_tpu.observe summary`` prints and what the
elastic supervisor persists as ``fleet.json`` at the end of a run.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional

from .events import merge_events

__all__ = ["scan_dir", "fleet_snapshot", "fleet_events", "write_fleet"]

METRICS_GLOB = "metrics-*.json"
EVENTS_GLOB = "events-*.jsonl"


def scan_dir(root: str) -> Dict[str, List[str]]:
    root = os.path.abspath(root)
    return {"metrics": sorted(glob.glob(os.path.join(root, METRICS_GLOB))),
            "events": sorted(glob.glob(os.path.join(root, EVENTS_GLOB)))}


def _load_metrics(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # torn/corrupt snapshot: skip, never fail the fleet view


def fleet_snapshot(root: str) -> dict:
    """Aggregate every worker's newest metric snapshot under ``root``.

    Partial-fleet tolerance (ISSUE 11 satellite): a missing, truncated or
    corrupt per-rank snapshot must not take the whole view down — the
    surviving ranks merge, the casualties are listed under ``partial``,
    and one ``fleet.partial`` run event is emitted (into the aggregating
    process's own sink, when it has one) so the degradation is visible in
    the stream instead of silently under-counting the fleet."""
    workers: Dict[str, dict] = {}
    latest: Dict[tuple, dict] = {}  # (host, rank) -> snapshot of max gen
    partial: List[str] = []
    for path in scan_dir(root)["metrics"]:
        snap = _load_metrics(path)
        if snap is None:
            partial.append(os.path.basename(path))
            continue
        meta = snap.get("meta", {})
        host = meta.get("host", os.path.basename(path))
        rank, gen = meta.get("rank", 0), meta.get("gen", 0)
        workers[f"{host}:r{rank}:g{gen}"] = snap
        key = (host, rank)
        if key not in latest or latest[key]["meta"].get("gen", 0) <= gen:
            latest[key] = snap
    summed: Dict[str, float] = {}
    for snap in latest.values():
        for name, v in snap.get("counters", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                summed[name] = summed.get(name, 0) + v
    gauges: Dict[str, dict] = {}
    for key, snap in latest.items():
        label = f"{key[0]}:r{key[1]}"
        for name, v in snap.get("gauges", {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauges.setdefault(name, {})[label] = v
    if partial:
        try:
            from . import emit

            emit("fleet.partial", root=os.path.abspath(root),
                 skipped=sorted(partial), survivors=sorted(workers))
        except Exception:
            pass
    return {"ts": time.time(), "root": os.path.abspath(root),
            "workers": sorted(workers),
            "counters_sum": summed,
            "gauges_by_worker": gauges,
            "partial": sorted(partial),
            "per_worker": workers}


def fleet_events(root: str) -> List[dict]:
    """Every worker generation's events, one wall-clock-ordered stream."""
    return merge_events(scan_dir(root)["events"])


def write_fleet(root: str, path: Optional[str] = None) -> Optional[str]:
    """Persist the aggregated snapshot as ``<root>/fleet.json`` (atomic).
    Returns the path, or None when nothing could be written."""
    snap = fleet_snapshot(root)
    path = path or os.path.join(os.path.abspath(root), "fleet.json")
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
