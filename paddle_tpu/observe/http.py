"""Localhost observability endpoint: ``/metrics`` + ``/healthz``.

A deliberately tiny stdlib HTTP server (no framework, no extra deps —
container constraint) bound to 127.0.0.1 only: this is a scrape target and
liveness probe for a sidecar/operator on the same host, NOT a public
service.  ``/metrics`` renders every registered provider's snapshot as one
Prometheus exposition document; ``/metrics.json`` returns the raw merged
JSON; ``/healthz`` returns 200 with the merged health dicts (503 when any
provider reports ``ok: false`` — the shape load balancers expect).

Providers are callables returning either a ``MetricsRegistry.snapshot()``
dict or a flat name->value mapping; the serving engine registers its own
``ServingMetrics`` view next to the process registry so the endpoint's
counters match ``ServingMetrics.snapshot()`` exactly (acceptance oracle in
tests/test_observe.py).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .export import prometheus_text

__all__ = ["MetricsServer"]


class MetricsServer:
    """Threaded localhost HTTP endpoint over a set of metric providers."""

    def __init__(self, port: int = 0,
                 providers: Optional[List[Callable[[], dict]]] = None,
                 health: Optional[Callable[[], dict]] = None):
        self._providers: List[Callable[[], dict]] = list(providers or [])
        self._health: List[Callable[[], dict]] = [health] if health else []
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr spam per scrape
                pass

            def do_GET(self):
                try:
                    if self.path.startswith("/metrics.json"):
                        body = json.dumps(server.merged()).encode()
                        ctype, code = "application/json", 200
                    elif self.path.startswith("/metrics"):
                        body = server.prometheus().encode()
                        ctype = "text/plain; version=0.0.4"
                        code = 200
                    elif self.path.startswith("/healthz"):
                        health = server.health()
                        code = 200 if health.get("ok", True) else 503
                        body = json.dumps(health).encode()
                        ctype = "application/json"
                    else:
                        body, ctype, code = b"not found", "text/plain", 404
                except Exception as exc:  # a broken provider != a dead port
                    body = f"provider error: {exc!r}".encode()
                    ctype, code = "text/plain", 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="observe-http", daemon=True)
        self._thread.start()

    # -- providers --
    def add_provider(self, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._providers.append(fn)

    def add_health(self, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._health.append(fn)

    # -- views --
    def merged(self) -> dict:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            providers = list(self._providers)
        for fn in providers:
            snap = fn() or {}
            if "counters" not in snap and "gauges" not in snap:
                snap = {"gauges": {k: v for k, v in snap.items()
                                   if isinstance(v, (int, float))
                                   and not isinstance(v, bool)}}
            for family in ("counters", "gauges", "histograms"):
                out[family].update(snap.get(family, {}))
        return out

    def prometheus(self) -> str:
        return prometheus_text(self.merged())

    def health(self) -> dict:
        out: Dict[str, object] = {"ok": True}
        with self._lock:
            health = list(self._health)
        for fn in health:
            h = fn() or {}
            if not h.get("ok", True):
                out["ok"] = False
            for k, v in h.items():
                if k != "ok":
                    out[k] = v
        return out

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
