"""SLO watchdog: rolling robust baselines, ``slo.breach`` run events.

The observe registry answers "what is the number"; this module answers
"did the number just get WORSE than this run's own normal".  For each
watched metric it keeps a bounded rolling window and a robust baseline
(median + MAD — one compile-spike or GC pause cannot drag the baseline
the way a mean would), and when a new observation exceeds
``factor x median`` AND clears the MAD noise guard it emits one
``slo.breach`` run event (stamped like every other record: host / rank /
gen / step / trace context) plus a ``slo.breaches{metric=...}`` counter.
That event/counter pair is the hook ROADMAP item 3's shed/scale policy
consumes: a router can watch the stream (or scrape the counter) instead
of re-deriving "is p99 regressing" from raw samples.

Fed from the paths that matter (all no-ops until ``PADDLE_SLO=1``):

 - ``executor.step_time_s``  — per-step time of every training dispatch
   (``Executor.run``/``run_steps`` and the sharded window runner);
 - ``train.step_time_s``     — the trainer's windowed-loop wall time per
   step, which INCLUDES input-feed stalls the executor never sees (this
   is the metric an injected ``PADDLE_FAULT_IO_DELAY_MS`` regresses);
 - ``train.data_wait_s``     — time the training loop blocked waiting on
   the input pipeline (``paddle_tpu.data.note_data_wait``: the prefetch
   consumer's per-window wait, or the per-step loop's batch pull) — an
   injected ``PADDLE_FAULT_DATA_STALL_MS`` stall breaches here and also
   emits a ``data.stall`` run event;
 - ``serving.latency_s``     — per-request queue+execute latency (tail
   regressions surface here before the lifetime p99 moves);
 - ``serving.queue_depth``   — the admission queue depth gauge;
 - ``memory.live_bytes``     — the live-buffer ledger's total device
   residency (``observe.memory``): monotonic growth across windows or
   elastic generations breaches like a slow step — leak detection; the
   ``PADDLE_FAULT_MEM_PRESSURE`` ramp is its deterministic oracle;
 - ``goodput.stall_s``       — per-interval stall-state time from the
   goodput accumulator (``observe.goodput``: data waits, barrier waits,
   synchronous checkpoint commits), so a run whose stall profile
   regresses breaches even while raw step time stays flat.  Straggler
   findings land in the SAME stream as ``straggler.detected{rank=}``
   records (emitted by the elastic supervisor's skew scan), next to the
   ``slo.breach`` events an autoscaler already consumes.

Env contract (``fluid.envcontract``): ``PADDLE_SLO`` arms it,
``PADDLE_SLO_FACTOR`` (default 3.0) is the regression factor,
``PADDLE_SLO_WINDOW`` / ``PADDLE_SLO_MIN_SAMPLES`` bound the baseline,
``PADDLE_SLO_COOLDOWN_S`` rate-limits repeat breaches per metric.
Baselines keep absorbing observations after a breach, so a *sustained*
level shift alarms until the window adapts (a page, then quiet), while a
one-off spike alarms exactly once.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["SLOWatchdog", "get_watchdog", "observe_value", "reset"]


def _median(sorted_vals) -> float:
    n = len(sorted_vals)
    mid = n // 2
    if n % 2:
        return float(sorted_vals[mid])
    return (sorted_vals[mid - 1] + sorted_vals[mid]) / 2.0


class SLOWatchdog:
    """Rolling median+MAD baseline per metric; breach detection on every
    observation.  Thread-safe (one lock; serving threads and the training
    loop feed it concurrently)."""

    def __init__(self, window: int = 64, factor: float = 3.0,
                 min_samples: int = 8, cooldown_s: float = 1.0):
        self.window = max(4, int(window))
        self.factor = float(factor)
        self.min_samples = max(2, int(min_samples))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._series: Dict[str, deque] = {}
        self._last_breach: Dict[str, float] = {}
        self.breaches: Dict[str, int] = {}

    def baseline(self, metric: str):
        """(median, mad, n) of the current rolling window for ``metric``
        (zeros when empty)."""
        with self._lock:
            vals = sorted(self._series.get(metric, ()))
        if not vals:
            return 0.0, 0.0, 0
        med = _median(vals)
        mad = _median(sorted(abs(v - med) for v in vals))
        return med, mad, len(vals)

    def observe(self, metric: str, value: float, **ctx) -> bool:
        """Feed one observation; returns True when it breached.  The
        check runs against the baseline of PRIOR samples, then the value
        joins the window (so the breach itself cannot mask a follow-up)."""
        value = float(value)
        breach = False
        med = mad = 0.0
        with self._lock:
            d = self._series.get(metric)
            if d is None:
                d = self._series[metric] = deque(maxlen=self.window)
            n = len(d)
            if n >= self.min_samples:
                vals = sorted(d)
                med = _median(vals)
                mad = _median(sorted(abs(v - med) for v in vals))
                # factor over the median is the SLO; the MAD term keeps
                # near-zero-variance metrics from alarming on noise
                if med > 0.0 and value > med * self.factor \
                        and value > med + 3.0 * mad:
                    now = time.perf_counter()
                    if now - self._last_breach.get(metric, -1e9) \
                            >= self.cooldown_s:
                        self._last_breach[metric] = now
                        self.breaches[metric] = \
                            self.breaches.get(metric, 0) + 1
                        breach = True
            d.append(value)
        if breach:
            self._emit(metric, value, med, mad, n, **ctx)
        return breach

    def _emit(self, metric: str, value: float, med: float, mad: float,
              n: int, **ctx) -> None:
        try:
            from . import emit, registry

            registry().inc("slo.breaches", labels={"metric": metric})
            emit("slo.breach", metric=metric, value=round(value, 6),
                 baseline_median=round(med, 6), baseline_mad=round(mad, 6),
                 factor=self.factor, baseline_n=n, **ctx)
        except Exception:
            pass  # the watchdog must never take down what it watches


# late-binding singleton (the observe Sink / compile_cache _UNSET pattern:
# a subprocess that sets PADDLE_SLO before first use is honored)
_UNSET = object()
_watchdog = _UNSET
_wd_lock = threading.Lock()


def get_watchdog() -> Optional[SLOWatchdog]:
    """The process watchdog, or None when ``PADDLE_SLO`` is off."""
    global _watchdog
    if _watchdog is _UNSET:
        with _wd_lock:
            if _watchdog is _UNSET:
                try:
                    from ..fluid import envcontract as ec

                    if not ec.get("PADDLE_SLO"):
                        _watchdog = None
                    else:
                        _watchdog = SLOWatchdog(
                            window=ec.get("PADDLE_SLO_WINDOW"),
                            factor=ec.get("PADDLE_SLO_FACTOR"),
                            min_samples=ec.get("PADDLE_SLO_MIN_SAMPLES"),
                            cooldown_s=ec.get("PADDLE_SLO_COOLDOWN_S"))
                except Exception:
                    _watchdog = None
    return _watchdog


def observe_value(metric: str, value: float, **ctx) -> bool:
    """Feed the process watchdog; no-op (False) when disarmed."""
    wd = get_watchdog()
    if wd is None:
        return False
    return wd.observe(metric, value, **ctx)


def reset() -> None:
    """Drop the singleton and re-arm env late-binding (test hook, called
    from ``observe.reset``)."""
    global _watchdog
    with _wd_lock:
        _watchdog = _UNSET
