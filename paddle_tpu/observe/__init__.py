"""paddle_tpu.observe — the unified observability subsystem (ISSUE 5).

Four subsystems (executor, serving engine, guardian, compile cache,
elastic supervisor) used to emit counters into ``fluid.profiler``'s
module-level plain dict: unlabeled, racy under serving threads, invisible
across processes, unexportable.  This package is the single place they all
emit into now:

 - :mod:`registry` — the process-wide thread-safe
   :class:`~paddle_tpu.observe.registry.MetricsRegistry` (counters /
   gauges / histograms / timings, label support);
 - :mod:`events`   — the structured run-event log (JSONL, stamped with
   host / rank / elastic generation / step / program fingerprint);
 - :mod:`export`   — Prometheus-text + JSON snapshot writers and the
   chrome-trace exporter;
 - :mod:`http`     — the localhost ``/metrics`` + ``/healthz`` endpoint;
 - :mod:`fleet`    — cross-process aggregation of many workers' files.

Env contract (late-bound, same pattern as ``compile_cache``: a subprocess
that sets the env before first use is honored with no import-order
dependency)::

    PADDLE_OBSERVE_DIR      enable file output, rooted here (events JSONL
                            + periodic metric snapshots per process)
    PADDLE_OBSERVE_FLUSH_S  snapshot flush interval, seconds (default 5)
    PADDLE_OBSERVE_PORT     serve /metrics + /healthz on 127.0.0.1:<port>
                            (0 picks an ephemeral port; the endpoint is
                            part of the sink, so it requires
                            PADDLE_OBSERVE_DIR to be set too)

CLI: ``python -m paddle_tpu.observe {tail,summary,export,serve}`` and
``--smoke`` (tier-1 CI round-trip).  Operate guide: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Optional

from .events import EventLog, host_name
from .registry import MetricsRegistry

__all__ = [
    "MetricsRegistry", "EventLog", "registry", "get_sink", "configure",
    "disable", "reset", "emit", "span", "note_step", "note_program",
    "note_mesh", "note_commit_step", "current_step", "current_program",
    "current_mesh", "current_commit_step",
    "http_server", "ENV_DIR", "ENV_FLUSH", "ENV_PORT",
    # submodules re-exported for discoverability: observe.trace (span
    # tracer + device-time attribution), observe.watchdog (SLO breaches),
    # observe.memory (HBM accounting + live-buffer ledger),
    # observe.goodput (wall-clock state accounting + straggler ledger)
    "trace", "watchdog", "memory", "goodput",
]

ENV_DIR = "PADDLE_OBSERVE_DIR"
ENV_FLUSH = "PADDLE_OBSERVE_FLUSH_S"
ENV_PORT = "PADDLE_OBSERVE_PORT"

# ---------------------------------------------------------------------------
# process-wide registry + execution context
# ---------------------------------------------------------------------------

_registry = MetricsRegistry()

# set by the executor at step boundaries / program (re)binds; read by every
# EventLog.emit so all subsystems' events correlate on (step, program)
# without plumbing arguments through their APIs.  Plain attribute writes —
# atomic under the GIL, and a torn read costs one stale stamp, not
# correctness.
_step: Optional[int] = None
_program: Optional[str] = None
_mesh: Optional[str] = None
_commit_step: Optional[int] = None


def registry() -> MetricsRegistry:
    """THE process metrics registry (``fluid.profiler.record_counter``'s
    backend; serving/guardian/compile-cache counters all land here)."""
    return _registry


def note_step(step: Optional[int]) -> None:
    global _step
    _step = step


def note_program(fingerprint: Optional[str]) -> None:
    """Record the executing program's fingerprint (first 12 hex chars are
    plenty for correlation) for event stamping."""
    global _program
    _program = fingerprint


def note_mesh(label: Optional[str]) -> None:
    """Record the executing mesh topology (``dp4xtp2``-style label from
    ``parallel.mesh.mesh_label``) for event stamping — so fleet views can
    distinguish what topology a trip/cache-hit/checkpoint happened on."""
    global _mesh
    _mesh = label


def note_commit_step(step: Optional[int]) -> None:
    """Record the last CHECKPOINT-COMMITTED step (set at every _SUCCESS
    write, single-process and sharded).  Heartbeat files carry it so
    ``incidents.jsonl`` shows progress-at-death and the goodput ledger can
    price the work a restart loses (``last_step - commit_step``)."""
    global _commit_step
    _commit_step = step


def current_step() -> Optional[int]:
    return _step


def current_program() -> Optional[str]:
    return _program


def current_mesh() -> Optional[str]:
    return _mesh


def current_commit_step() -> Optional[int]:
    return _commit_step


# ---------------------------------------------------------------------------
# sink: the per-process file/endpoint writer
# ---------------------------------------------------------------------------


class Sink:
    """Owns this process's observability outputs: the event log file, the
    periodic metric-snapshot flusher, and (optionally) the HTTP endpoint.

    One sink per process; files are named for the (host, rank, generation)
    stamp so concurrent workers and successive elastic generations never
    share a file (``fleet`` merges them)."""

    def __init__(self, root: str, flush_s: Optional[float] = None,
                 port: Optional[int] = None, *,
                 host: Optional[str] = None, rank: Optional[int] = None,
                 gen: Optional[int] = None,
                 reg: Optional[MetricsRegistry] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.registry = reg if reg is not None else _registry
        self.host = host if host is not None else host_name()
        self.rank = int(rank if rank is not None
                        else os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
        self.gen = int(gen if gen is not None
                       else os.environ.get("PADDLE_ELASTIC_GENERATION",
                                           "0") or 0)
        self._stem = f"{self.host}-r{self.rank}-g{self.gen}"
        self.events = EventLog(
            os.path.join(self.root, f"events-{self._stem}.jsonl"),
            host=self.host, rank=self.rank, gen=self.gen)
        if flush_s is None:
            try:
                flush_s = float(os.environ.get(ENV_FLUSH, "") or 5.0)
            except ValueError:
                flush_s = 5.0
        self.flush_s = max(0.05, float(flush_s))
        self.server = None
        if port is None:
            p = os.environ.get(ENV_PORT, "").strip()
            port = int(p) if p else None
        if port is not None:
            from .http import MetricsServer

            self.server = MetricsServer(
                port, providers=[self.registry.snapshot],
                health=lambda: {"ok": True, "host": self.host,
                                "rank": self.rank, "gen": self.gen})
        self._stop = threading.Event()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="observe-flusher", daemon=True)
        self._flusher.start()
        # short-lived workers (one elastic generation) must still leave a
        # final snapshot behind for the fleet aggregator
        atexit.register(self.flush)

    def metrics_stem(self) -> str:
        return f"metrics-{self._stem}"

    def flush(self) -> None:
        """Write this process's metric snapshot files (atomic)."""
        from .export import write_snapshot

        try:
            write_snapshot(
                self.root, self.registry.snapshot(),
                stem=self.metrics_stem(),
                meta={"host": self.host, "rank": self.rank, "gen": self.gen,
                      "pid": os.getpid(), "ts": time.time(),
                      "step": current_step()})
        except OSError:
            pass  # a full disk must not take the training down with it

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_s):
            self.flush()

    def close(self) -> None:
        self._stop.set()
        if self.server is not None:
            self.server.close()
        self.flush()
        try:
            atexit.unregister(self.flush)
        except Exception:
            pass


# late-binding singleton (same _UNSET contract as compile_cache.get_store)
_UNSET = object()
_sink = _UNSET
_sink_lock = threading.Lock()


def get_sink() -> Optional[Sink]:
    """The process sink, built lazily from the env; None = file output and
    endpoint disabled (the in-memory registry always works)."""
    global _sink
    if _sink is _UNSET:
        with _sink_lock:
            if _sink is _UNSET:
                d = os.environ.get(ENV_DIR, "").strip()
                if not d:
                    _sink = None
                else:
                    try:
                        _sink = Sink(d)
                    except Exception:
                        _sink = None  # unusable dir must not fail the run
    return _sink


def configure(root: str, flush_s: Optional[float] = None,
              port: Optional[int] = None, **kw) -> Sink:
    """Enable programmatically (overrides the env)."""
    global _sink
    with _sink_lock:
        if _sink not in (None, _UNSET):
            _sink.close()
        _sink = Sink(root, flush_s=flush_s, port=port, **kw)
    return _sink


def disable() -> None:
    global _sink
    with _sink_lock:
        if _sink not in (None, _UNSET):
            _sink.close()
        _sink = None


def reset() -> None:
    """Close the sink, clear the registry and context, and re-arm env
    late-binding.  Test-harness hook (tests/conftest.py)."""
    global _sink, _step, _program, _mesh, _commit_step
    with _sink_lock:
        if _sink not in (None, _UNSET):
            _sink.close()
        _sink = _UNSET
    _registry.clear()
    _registry.stop_sampling()
    _step = None
    _program = None
    _mesh = None
    _commit_step = None
    # span tracer + SLO watchdog + memory ledger + goodput accumulator
    # piggyback on the sink lifecycle: re-arm their env late-binding /
    # clear their state with it
    from . import goodput as _goodput
    from . import memory as _memory
    from . import trace as _trace
    from . import watchdog as _watchdog

    _trace.reset()
    _watchdog.reset()
    _memory.reset()
    _goodput.reset()


def http_server():
    """The sink's MetricsServer, or None (serving engine attaches its
    provider here when the env endpoint is up)."""
    sink = get_sink()
    return sink.server if sink is not None else None


# ---------------------------------------------------------------------------
# module-level emit helpers (the API subsystems call)
# ---------------------------------------------------------------------------


def emit(event: str, **fields) -> Optional[dict]:
    """Append one stamped record to the process event log; no-op (None)
    when no observe dir is configured.  Never raises."""
    try:
        sink = get_sink()
        if sink is None:
            return None
        return sink.events.emit(event, **fields)
    except Exception:
        return None


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def span(event: str, **fields):
    """Timed-region context manager (emits ``dur_s``); no-op without a
    sink.  For PARENTED spans with trace identity use
    :func:`paddle_tpu.observe.trace.span` — this one predates the tracer
    and stays for plain flat timings."""
    try:
        sink = get_sink()
        if sink is None:
            return _NullSpan()
        return sink.events.span(event, **fields)
    except Exception:
        return _NullSpan()


# submodules imported last (they only import observe lazily, so there is
# no cycle): observe.trace / observe.watchdog / observe.memory /
# observe.goodput are part of the public API
from . import goodput, memory, trace, watchdog  # noqa: E402,F401  (re-export)
