"""Operator CLI for the observability subsystem (docs/OBSERVABILITY.md).

Commands (default dir: $PADDLE_OBSERVE_DIR, overridable via --dir)::

    python -m paddle_tpu.observe tail [--n 20] [--event guardian_trip]
                                     [--follow] [--grep PATTERN]
                                     # newest merged events, one JSON/line;
                                     # --follow poll-tails the whole fleet
                                     # dir (new generations picked up
                                     # live), --grep regex-filters lines
    python -m paddle_tpu.observe goodput
                                     # wall-clock state ledger from the
                                     # persisted stream: per-rank + fleet
                                     # seconds by state (device/compile/
                                     # data_wait/checkpoint/barrier/
                                     # restart/idle), goodput fraction,
                                     # restarts priced in lost steps,
                                     # cross-rank straggler verdicts
    python -m paddle_tpu.observe summary
                                     # aggregated fleet snapshot JSON
    python -m paddle_tpu.observe export --out trace.json
                                     # merged chrome://tracing file
    python -m paddle_tpu.observe serve [--port 9102]
                                     # /metrics + /healthz over the
                                     # aggregated fleet view
    python -m paddle_tpu.observe trace [--trace-id ID]
                                     # span trees: every trace in the
                                     # merged stream as an indented tree
                                     # (durations, host:rank:gen stamps)
    python -m paddle_tpu.observe memory
                                     # HBM summary: memory.* gauges,
                                     # latest memory.profile per
                                     # executable, ledger high-water,
                                     # serving bucket bytes, over-budget
                                     # incidents
    python -m paddle_tpu.observe --smoke
                                     # CI round-trip oracle (tier-1, <2s
                                     # after interpreter start; pattern of
                                     # tools/cache_ctl.py --smoke)

``--smoke`` exercises the full surface in a temp dir with NO accelerator
work: two simulated workers (distinct host/rank sinks) emit counters,
histograms and events; then the race oracle (8 threads x 2000 increments
must total exactly 16000), the Prometheus round-trip (render -> parse ->
same values), the live HTTP endpoint, fleet aggregation (summed counters
across workers), event merge ordering, and the chrome-trace export are all
checked, printing one JSON report and exiting non-zero on any failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _dir_or_die(args) -> str:
    d = args.dir or os.environ.get("PADDLE_OBSERVE_DIR", "").strip()
    if not d:
        print(json.dumps({"error": "no observe dir: pass --dir or set "
                                   "PADDLE_OBSERVE_DIR"}))
        raise SystemExit(2)
    return d


def cmd_tail(args) -> int:
    import re as _re

    from .fleet import fleet_events, follow_events

    root = _dir_or_die(args)
    grep = _re.compile(args.grep) if args.grep else None

    def keep(rec, line) -> bool:
        if args.event and rec.get("event") != args.event:
            return False
        return grep is None or bool(grep.search(line))

    recs = fleet_events(root)
    shown = [r for r in recs if keep(r, json.dumps(r))]
    for rec in shown[-args.n:]:
        print(json.dumps(rec))
    if not args.follow:
        return 0
    # live fleet debugging: poll-based tail -f over every event file in
    # the dir (new generations' files join automatically; the history
    # above is not re-printed)
    try:
        for rec in follow_events(root, poll_s=args.interval,
                                 from_end=True):
            line = json.dumps(rec)
            if keep(rec, line):
                print(line, flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_goodput(args) -> int:
    """The fleet-health answer (ISSUE 13): how much wall-clock trained,
    where the rest went, what each restart cost, and which rank drags —
    all re-derived from the persisted event stream, no live process."""
    from .fleet import fleet_events, rank_skew
    from .goodput import build_ledger

    recs = fleet_events(_dir_or_die(args))
    ledger = build_ledger(recs)
    skew = rank_skew(recs)
    out = {k: ledger[k] for k in ("workers", "ranks", "states", "total_s",
                                  "fraction", "restarts",
                                  "straggler_events")}
    out["skew"] = skew
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def cmd_summary(args) -> int:
    from .fleet import fleet_events, fleet_snapshot

    root = _dir_or_die(args)
    snap = fleet_snapshot(root)
    events = fleet_events(root)
    kinds = {}
    for r in events:
        kinds[r.get("event", "?")] = kinds.get(r.get("event", "?"), 0) + 1
    out = {"root": snap["root"], "workers": snap["workers"],
           "counters_sum": snap["counters_sum"],
           "gauges_by_worker": snap["gauges_by_worker"],
           "events_total": len(events), "events_by_kind": kinds}
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def cmd_export(args) -> int:
    from .export import chrome_trace
    from .fleet import fleet_events
    from .goodput import build_ledger

    recs = fleet_events(_dir_or_die(args))
    # the ledger's swept per-rank state segments draw as a "goodput
    # state" thread row under each rank's spans
    try:
        segments = build_ledger(recs)["segments"]
    except Exception:
        segments = None
    trace = chrome_trace(recs, device_trace_dir=args.device_trace_dir,
                         goodput_segments=segments)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    print(json.dumps({"out": args.out, "events": len(recs),
                      "goodput_segments": len(segments or []),
                      "pids": len({(r.get('host'), r.get('rank'))
                                   for r in recs})}))
    return 0


def cmd_trace(args) -> int:
    """Render the merged span stream as per-trace trees (the text twin of
    the chrome-trace export: same records, no browser needed)."""
    from .fleet import fleet_events

    recs = fleet_events(_dir_or_die(args))
    spans = [r for r in recs if r.get("span_id")]
    by_trace = {}
    for r in spans:
        by_trace.setdefault(r.get("trace_id") or "?", []).append(r)
    if args.trace_id:
        by_trace = {k: v for k, v in by_trace.items()
                    if k.startswith(args.trace_id)}
    for trace_id in sorted(by_trace):
        recs_t = by_trace[trace_id]
        ids = {r["span_id"] for r in recs_t}
        kids = {}
        roots = []
        for r in recs_t:
            parent = r.get("parent_span")
            if parent and parent in ids:
                kids.setdefault(parent, []).append(r)
            else:
                roots.append(r)
        print(f"trace {trace_id}  ({len(recs_t)} spans, "
              f"{len(roots)} roots)")

        def _start(r):
            return r.get("ts", 0) - (r.get("dur_s") or 0)

        def _walk(r, depth):
            dur = r.get("dur_s")
            dur_s = f"{dur * 1e3:10.3f} ms" if dur is not None else " " * 13
            stamp = f"{r.get('host', '?')}:r{r.get('rank', 0)}" \
                    f":g{r.get('gen', 0)}"
            print(f"  {dur_s}  {'  ' * depth}{r.get('event', '?')}"
                  f"  [{stamp} span={r['span_id'][:8]}]")
            for k in sorted(kids.get(r["span_id"], []), key=_start):
                _walk(k, depth + 1)

        for r in sorted(roots, key=_start):
            _walk(r, 0)
    if not by_trace:
        print(json.dumps({"traces": 0,
                          "note": "no span records found (is tracing "
                                  "enabled? PADDLE_TRACE / an observe "
                                  "dir must be set on the traced run)"}))
    return 0


def cmd_memory(args) -> int:
    """HBM summary: compiled-truth gauges, latest memory.profile per
    executable kind/mesh, ledger high-water per (scope, mesh), serving
    bucket footprints and over-budget incidents — the text answer to
    'what is this fleet spending device memory on'."""
    from .fleet import fleet_events, fleet_snapshot

    root = _dir_or_die(args)
    snap = fleet_snapshot(root)
    gauges = {name: by for name, by in snap["gauges_by_worker"].items()
              if name.startswith(("memory.", "serving.bucket_bytes",
                                  "analysis.mem_peak_est"))}
    profiles = {}
    watermarks = {}
    over_budget = []
    for r in fleet_events(root):
        ev = r.get("event")
        if ev == "memory.profile":
            key = f"{r.get('kind') or '?'}@{r.get('mesh') or 'single'}"
            profiles[key] = {k: r.get(k) for k in (
                "peak_bytes", "argument_bytes", "output_bytes",
                "temp_bytes", "generated_code_bytes", "cached", "n_steps",
                "ts")}
        elif ev == "memory.watermark":
            key = f"{r.get('scope') or '?'}@{r.get('mesh') or 'single'}"
            cur = watermarks.get(key, {})
            watermarks[key] = {
                "live_bytes": r.get("live_bytes"),
                "high_water_bytes": max(cur.get("high_water_bytes") or 0,
                                        r.get("high_water_bytes") or 0),
                "samples": cur.get("samples", 0) + 1}
        elif ev == "memory.over_budget":
            over_budget.append({k: r.get(k) for k in (
                "ts", "scope", "mesh", "total_bytes", "budget_mb")})
    print(json.dumps({"root": snap["root"],
                      "workers": snap["workers"],
                      "partial": snap.get("partial", []),
                      "gauges_by_worker": gauges,
                      "profiles": profiles,
                      "watermarks": watermarks,
                      "over_budget": over_budget[-10:]},
                     indent=1, sort_keys=True))
    return 0


def cmd_serve(args) -> int:
    from .fleet import fleet_snapshot
    from .http import MetricsServer

    root = _dir_or_die(args)

    def provider():
        snap = fleet_snapshot(root)
        return {"counters": snap["counters_sum"],
                "gauges": {f'{n}{{worker="{w}"}}': v
                           for n, by in snap["gauges_by_worker"].items()
                           for w, v in by.items()},
                "histograms": {}}

    srv = MetricsServer(args.port, providers=[provider],
                        health=lambda: {"ok": True, "root": root})
    print(json.dumps({"serving": f"http://127.0.0.1:{srv.port}/metrics",
                      "healthz": f"http://127.0.0.1:{srv.port}/healthz"}))
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        srv.close()
    return 0


# ---------------------------------------------------------------------------
# smoke
# ---------------------------------------------------------------------------


def cmd_smoke(_args) -> int:
    import shutil
    import tempfile
    import threading
    import urllib.request

    from . import Sink, registry, reset
    from .export import parse_prometheus_text, prometheus_text, chrome_trace
    from .fleet import fleet_events, fleet_snapshot
    from .registry import MetricsRegistry

    t_start = time.perf_counter()
    root = tempfile.mkdtemp(prefix="observe_smoke_")
    report = {"ok": False, "root": root}
    sinks = []
    try:
        # -- 1. the race oracle: N threads x M increments == exactly N*M
        reg = registry()
        n_threads, m_incs = 8, 2000

        def hammer():
            for _ in range(m_incs):
                reg.inc("smoke.race")

        threads = [threading.Thread(target=hammer)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report["race_total"] = reg.flat().get("smoke.race")
        report["race_exact"] = report["race_total"] == n_threads * m_incs

        # -- 2. two simulated workers, each with its own sink + registry
        for i, host in enumerate(("hostA", "hostB")):
            wreg = MetricsRegistry()
            wreg.inc("smoke.requests", 5 + i)
            wreg.set_gauge("smoke.queue_depth", i)
            wreg.observe("smoke.latency_s", 0.004 + i * 0.01)
            sink = Sink(root, flush_s=60.0, host=host, rank=i, gen=0,
                        reg=wreg)
            sink.events.emit("smoke.worker_start", idx=i)
            sink.events.emit("smoke.worker_done", idx=i)
            sink.flush()
            sinks.append(sink)

        # -- 3. Prometheus round trip on worker 0's registry
        snap0 = sinks[0].registry.snapshot()
        parsed = parse_prometheus_text(prometheus_text(snap0))
        report["prom_round_trip"] = (
            parsed["counters"].get("smoke_requests") == 5
            and parsed["gauges"].get("smoke_queue_depth") == 0
            and parsed["histograms"].get("smoke_latency_s",
                                         {}).get("count") == 1)

        # -- 4. live endpoint over the process registry
        from .http import MetricsServer

        srv = MetricsServer(0, providers=[reg.snapshot],
                            health=lambda: {"ok": True})
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=5).read().decode()
        health = json.loads(urllib.request.urlopen(
            f"{base}/healthz", timeout=5).read().decode())
        srv.close()
        scraped = parse_prometheus_text(text)
        report["endpoint_counter_matches"] = (
            scraped["counters"].get("smoke_race") == report["race_total"])
        report["healthz_ok"] = bool(health.get("ok"))

        # -- 5. fleet aggregation: summed counters + merged events
        fsnap = fleet_snapshot(root)
        report["fleet_workers"] = fsnap["workers"]
        report["fleet_sum"] = fsnap["counters_sum"].get("smoke.requests")
        report["fleet_sum_exact"] = report["fleet_sum"] == 5 + 6
        events = fleet_events(root)
        report["events_total"] = len(events)
        report["events_sorted"] = all(
            events[i]["ts"] <= events[i + 1]["ts"]
            for i in range(len(events) - 1))
        report["events_stamped"] = all(
            {"host", "rank", "gen", "pid"} <= set(r) for r in events)

        # -- 6. chrome-trace export: one pid per (host, rank)
        trace = chrome_trace(events)
        pids = {e["pid"] for e in trace["traceEvents"]
                if e.get("ph") != "M"}
        names = [e for e in trace["traceEvents"]
                 if e.get("name") == "process_name"]
        report["trace_pids"] = sorted(pids)
        report["trace_distinct_pids"] = len(pids) == 2 and len(names) == 2

        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = all(report[k] for k in (
            "race_exact", "prom_round_trip", "endpoint_counter_matches",
            "healthz_ok", "fleet_sum_exact", "events_sorted",
            "events_stamped", "trace_distinct_pids"))
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        for sink in sinks:
            sink.close()
        reset()
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observe",
        description="Inspect / export / serve observability data.")
    ap.add_argument("command", nargs="?", default="summary",
                    choices=["tail", "summary", "export", "serve", "trace",
                             "memory", "goodput"])
    ap.add_argument("--dir", default=None,
                    help="observe dir (default $PADDLE_OBSERVE_DIR)")
    ap.add_argument("--n", type=int, default=20, help="tail: line count")
    ap.add_argument("--event", default=None,
                    help="tail: only this event kind")
    ap.add_argument("--follow", "-f", action="store_true",
                    help="tail: keep polling for new events (tail -f)")
    ap.add_argument("--grep", default=None,
                    help="tail: only lines matching this regex")
    ap.add_argument("--interval", type=float, default=0.5,
                    help="tail --follow: poll interval seconds")
    ap.add_argument("--trace-id", default=None,
                    help="trace: only traces whose id starts with this")
    ap.add_argument("--out", default="timeline.json",
                    help="export: chrome-trace output path")
    ap.add_argument("--device-trace-dir", default=None,
                    help="export: jax trace dir to reference")
    ap.add_argument("--port", type=int, default=0,
                    help="serve: port (0 = ephemeral)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI round-trip in a temp dir")
    args = ap.parse_args(argv)
    if args.smoke:
        return cmd_smoke(args)
    try:
        return {"tail": cmd_tail, "summary": cmd_summary,
                "export": cmd_export, "serve": cmd_serve,
                "trace": cmd_trace, "memory": cmd_memory,
                "goodput": cmd_goodput}[args.command](args)
    except BrokenPipeError:
        # `... | head` closing stdout early is normal unix usage, not an
        # error worth a traceback
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
