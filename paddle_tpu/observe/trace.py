"""Distributed span tracer + device-time attribution (ISSUE 9 tentpole).

PR 5's run-event stream answers *what happened*; this module answers
*where the time went*.  A span is one timed region with W3C-style
identity — a 32-hex ``trace_id`` shared by everything in one logical
run/request and a 16-hex ``span_id`` per region, with ``parent_span``
links forming the tree — emitted into the SAME per-process run-event
JSONL the fleet aggregator already merges, so one ``chrome://tracing``
export shows supervisor generations, executor windows, prefetch staging
on its worker thread, and per-request serving breakdowns as nested
duration events.

API surface (all no-ops returning ``None`` when tracing is off):

 - ``span(name, **attrs)`` — context manager; pushes the span onto the
   calling thread's context stack so nested spans parent automatically
   and every ``observe.emit`` record inside is stamped with
   (trace_id, span_id);
 - ``start_span(name, parent=..., **attrs)`` / ``Span.end(**attrs)`` —
   explicit pair for async hand-offs (a serving request's span lives
   across the batcher thread; a prefetch stage span lives on the worker
   thread);
 - ``emit_span(name, t0, t1, parent=...)`` — record an already-measured
   ``perf_counter`` interval as a child span (queue-wait spans are known
   only after the fact).

Enablement: ``PADDLE_TRACE`` (default on) gates everything, and spans
only materialize when an observe sink exists (``PADDLE_OBSERVE_DIR``) —
so production runs without an observe dir pay a single dict lookup per
window, and ``PADDLE_TRACE=0`` forces the hot paths back to their exact
pre-trace shape (no device sync, no extra lowering).
``PADDLE_TRACE_SAMPLE`` keeps every Nth root span (deterministic
counter-based sampling — no RNG on the hot path); children inherit their
root's decision by construction (an unsampled root returns ``None`` and
its would-be children become roots of their own sampling decision).

Cross-process stitching: ``PADDLE_TRACEPARENT`` (W3C ``traceparent``
shape, ``00-<trace>-<span>-01``) seeds this process's trace id and
default root parent.  The elastic supervisor mints ONE trace id per run,
opens a span per generation, and hands each generation
``PADDLE_TRACEPARENT`` pointing at its generation span — so a
kill-and-resume run merges into one trace tree spanning processes.

Device-time attribution: :func:`cost_of` reads ``cost_analysis()`` off a
jax ``Lowered``/``Compiled`` (flops + bytes accessed of the whole fused
window program) and :func:`note_device_cost` turns it into the
``device.flops_per_window`` / ``device.mfu{mesh=...}`` gauges
(model-flops-utilization = flops / wall / peak);
:func:`note_window_breakdown` publishes the per-window
``window.host_ms`` / ``window.stage_ms`` / ``window.device_ms`` /
``window.observe_ms`` gauge family the step-time breakdown view reads.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Optional

__all__ = [
    "Span", "span", "start_span", "emit_span", "current", "enabled",
    "trace_context", "set_trace_context", "new_span_id",
    "format_traceparent", "parse_traceparent", "thread_tid",
    "cost_of", "device_peak_flops", "note_device_cost",
    "note_window_breakdown", "reset",
]

# one wall/perf anchor pair so perf_counter intervals map onto the event
# log's unix-seconds timebase consistently within a process
_PERF0 = time.perf_counter()
_WALL0 = time.time()


def _wall(perf_t: float) -> float:
    return _WALL0 + (perf_t - _PERF0)


def _gen_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


# ---------------------------------------------------------------------------
# process trace context + thread-local span stack
# ---------------------------------------------------------------------------

_tls = threading.local()
_state_lock = threading.Lock()
_trace_id: Optional[str] = None    # lazily: env traceparent or random
_env_parent: Optional[str] = None  # parent span id inherited from the env
_root_seq = itertools.count(1)     # deterministic sampling sequence
_tid_lock = threading.Lock()
_tids = {}                         # thread ident -> small stable int


def thread_tid() -> int:
    """Small stable per-thread integer (chrome-trace ``tid``), assigned
    in first-use order so the executor thread is usually tid 0."""
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        with _tid_lock:
            t = _tids.setdefault(ident, len(_tids))
    return t


def parse_traceparent(raw: str):
    """(trace_id, span_id) out of a W3C-ish traceparent string; tolerant
    of the bare ``<trace>`` and ``<trace>-<span>`` shapes."""
    parts = [p for p in (raw or "").strip().split("-") if p]
    # strip the W3C version/flags fields when present
    if parts and len(parts[0]) <= 2:
        parts = parts[1:]
    if parts and len(parts[-1]) <= 2:
        parts = parts[:-1]
    if not parts:
        return None, None
    trace = parts[0] if len(parts[0]) >= 16 else None
    parent = parts[1] if len(parts) > 1 and len(parts[1]) >= 8 else None
    return trace, parent


def format_traceparent(trace_id: str, span_id: Optional[str]) -> str:
    return f"00-{trace_id}-{span_id or '0' * 16}-01"


def trace_context():
    """This process's (trace_id, inherited parent span id).  Adopted from
    ``PADDLE_TRACEPARENT`` on first use (late-bound, same contract as the
    observe sink) or minted fresh."""
    global _trace_id, _env_parent
    if _trace_id is None:
        with _state_lock:
            if _trace_id is None:
                from ..fluid import envcontract

                tid, pid = parse_traceparent(
                    envcontract.get("PADDLE_TRACEPARENT") or "")
                _env_parent = pid
                _trace_id = tid or _gen_id(16)
    return _trace_id, _env_parent


def set_trace_context(trace_id: Optional[str],
                      parent_span: Optional[str] = None) -> None:
    """Pin the process trace context programmatically (the supervisor
    uses this for its own records; tests use it for determinism)."""
    global _trace_id, _env_parent
    with _state_lock:
        _trace_id = trace_id
        _env_parent = parent_span


def new_span_id() -> str:
    return _gen_id(8)


def current() -> Optional["Span"]:
    """The calling thread's innermost open ``span(...)`` context."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def enabled() -> bool:
    """Tracing is on: ``PADDLE_TRACE`` truthy AND an observe sink exists
    (spans land in the run-event stream; without a stream there is
    nowhere to put them, so the hot paths skip all measurement)."""
    from ..fluid import envcontract

    if not envcontract.get("PADDLE_TRACE"):
        return False
    from . import get_sink

    return get_sink() is not None


def _sample_root() -> bool:
    from ..fluid import envcontract

    try:
        rate = float(envcontract.get("PADDLE_TRACE_SAMPLE"))
    except (TypeError, ValueError):
        rate = 1.0
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    n = next(_root_seq)
    return int(n * rate) != int((n - 1) * rate)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def _do_emit(emit_fn, event: str, **fields) -> None:
    try:
        if emit_fn is None:
            from . import emit as emit_fn
        emit_fn(event, **fields)
    except Exception:
        pass  # telemetry must never fail the work it measures


class Span:
    """One open timed region.  ``end()`` emits a single run-event record
    carrying ``dur_s`` + the trace identity; it is idempotent, returns
    the duration in seconds, and never raises."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "tid", "ended", "_t0", "_emit")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: dict, emit_fn=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _gen_id(8)
        self.parent_id = parent_id
        self.attrs = attrs
        self.tid = thread_tid()
        self.ended = False
        self._t0 = time.perf_counter()
        self._emit = emit_fn

    def end(self, **extra) -> Optional[float]:
        if self.ended:
            return None
        self.ended = True
        t1 = time.perf_counter()
        dur = t1 - self._t0
        fields = dict(self.attrs)
        fields.update(extra)
        _do_emit(self._emit, self.name, ts=_wall(t1),
                 dur_s=round(dur, 6), trace_id=self.trace_id,
                 span_id=self.span_id, parent_span=self.parent_id,
                 tid=self.tid, **fields)
        return dur


def start_span(name: str, parent: Optional[Span] = None, emit_fn=None,
               **attrs) -> Optional[Span]:
    """Open a span WITHOUT touching the thread context stack (async
    hand-off form — the opener and the closer may be different threads).
    Returns None when tracing is off or the root sampler says skip."""
    try:
        if not enabled():
            return None
        if parent is None:
            parent = current()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            if not _sample_root():
                return None
            trace_id, parent_id = trace_context()
        return Span(name, trace_id, parent_id, attrs, emit_fn)
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attrs):
    """Context-manager span: children opened inside parent to it, and
    ``observe.emit`` records inside are stamped with its identity.
    Yields the Span (or None when tracing is off/sampled out)."""
    sp = start_span(name, **attrs)
    if sp is None:
        yield None
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(sp)
    try:
        yield sp
    finally:
        if stack and stack[-1] is sp:
            stack.pop()
        sp.end()


def emit_span(name: str, t0: float, t1: float,
              parent: Optional[Span] = None, emit_fn=None,
              **attrs) -> Optional[str]:
    """Record an already-measured ``perf_counter`` interval as a child of
    ``parent`` (queue waits, H2D staging, dispatch segments — intervals
    whose boundaries are only known after the fact).  Returns the new
    span id, or None when there is no live parent to hang it off."""
    if parent is None:
        return None
    try:
        span_id = _gen_id(8)
        _do_emit(emit_fn, name, ts=_wall(t1),
                 dur_s=round(max(0.0, t1 - t0), 6),
                 trace_id=parent.trace_id, span_id=span_id,
                 parent_span=parent.span_id, tid=thread_tid(), **attrs)
        return span_id
    except Exception:
        return None


# ---------------------------------------------------------------------------
# device-time attribution: compiled cost -> flops/MFU/breakdown gauges
# ---------------------------------------------------------------------------

#: peak dense bf16 TFLOPs per chip by TPU generation (device_kind
#: substrings, bench.py's table); CPU gets a nominal figure so MFU stays
#: a defined diagnostic ratio on the test backend.
PEAK_BF16_TFLOPS = (
    ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0), ("v5 lite", 197.0),
    ("v5litepod", 197.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
)
CPU_NOMINAL_TFLOPS = 0.5  # per-core-class placeholder, documented nominal


def device_peak_flops(device=None) -> float:
    """Peak FLOPs/s of ``device`` (default: the first jax device)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    if getattr(device, "platform", "cpu") == "cpu":
        return CPU_NOMINAL_TFLOPS * 1e12
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, tflops in PEAK_BF16_TFLOPS:
        if key in kind:
            return tflops * 1e12
    return 197.0 * 1e12  # unknown generation: assume v5e-class


def cost_of(stage) -> Optional[dict]:
    """``{"flops": f, "bytes": b}`` from a jax ``Lowered`` or ``Compiled``
    stage's ``cost_analysis()`` (list-of-dict on some backends); None when
    the backend exposes no cost model."""
    try:
        ca = stage.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0:
        return None
    return {"flops": flops, "bytes": nbytes}


def note_device_cost(cost: Optional[dict], wall_s: float, n_steps: int,
                     mesh: Optional[str] = None, device=None) -> Optional[float]:
    """Publish the device-attribution gauges for one executed window:
    ``device.flops_per_window`` / ``device.bytes_per_window`` (the whole
    fused program's cost) and ``device.mfu{mesh=...}`` = flops / wall /
    peak.  Returns the MFU, or None when no cost is available."""
    if not cost or wall_s <= 0.0:
        return None
    try:
        from . import registry

        reg = registry()
        labels = {"mesh": mesh} if mesh else None
        reg.set_gauge("device.flops_per_window", cost["flops"],
                      labels=labels)
        reg.set_gauge("device.bytes_per_window", cost["bytes"],
                      labels=labels)
        mfu = cost["flops"] / wall_s / device_peak_flops(device)
        reg.set_gauge("device.mfu", mfu, labels=labels)
        reg.set_gauge("device.flops_per_sec", cost["flops"] / wall_s,
                      labels=labels)
        return mfu
    except Exception:
        return None


def note_window_breakdown(host_ms: float, stage_ms: float,
                          device_ms: float, observe_ms: float,
                          mesh: Optional[str] = None) -> None:
    """The per-window step-time breakdown gauge family: host-side prep /
    H2D staging / device execution / host observe tail, milliseconds."""
    try:
        from . import registry

        reg = registry()
        labels = {"mesh": mesh} if mesh else None
        for name, v in (("window.host_ms", host_ms),
                        ("window.stage_ms", stage_ms),
                        ("window.device_ms", device_ms),
                        ("window.observe_ms", observe_ms)):
            reg.set_gauge(name, round(float(v), 3), labels=labels)
    except Exception:
        pass


def reset() -> None:
    """Re-arm env late-binding and clear this thread's context stack
    (test-harness hook, called from ``observe.reset``)."""
    global _trace_id, _env_parent
    with _state_lock:
        _trace_id = None
        _env_parent = None
    if getattr(_tls, "stack", None):
        _tls.stack = []
