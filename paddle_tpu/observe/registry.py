"""Thread-safe metrics registry: counters, gauges, histograms, timings.

The predecessor was ``fluid.profiler``'s module-level plain dicts — an
unlocked read-modify-write per increment that silently dropped updates
whenever serving workers, the guardian's observer, and the training loop
emitted concurrently (ISSUE 5 satellite: N threads x M increments must be
exactly N*M).  Every mutation here happens under ONE re-entrant lock, which
is also exported (``registry.lock``) so adjacent aggregation state that
must stay consistent with the metrics (the profiler's timeline) can share
it instead of growing a second lock with ordering rules.

Metric model (deliberately the Prometheus one, so the text exporter is a
straight rendering):

 - **counter**: monotonically accumulating float/int (``inc``);
 - **gauge**: last-write-wins absolute value (``set_gauge``);
 - **histogram**: cumulative bucket counts + sum + count (``observe``);
 - **timing**: the reference profiler's [calls, total, min, max] aggregate
   per event name (``record_timing``) — host-span statistics that back
   ``fluid.profiler.stop_profiler``'s table.

Labels: any metric accepts ``labels={...}``; the (name, sorted label
items) pair is the identity.  The flat rendering is the Prometheus exposition
form ``name{k="v"}``.

Naming scheme (docs/OBSERVABILITY.md): dot-separated
``<subsystem>.<metric>`` — e.g. ``compile_cache.hit``,
``executor.jit_cache.size``, ``serving.completed``, ``guardian_trips``
(pre-existing flat names are kept for compatibility).  The Prometheus
exporter maps dots to underscores.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["MetricsRegistry", "render_name", "split_name",
           "DEFAULT_BUCKETS"]

#: default histogram bucket upper bounds, in seconds — log-spaced to cover
#: sub-ms serving latencies through multi-second compiles
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labels: Optional[dict]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def render_name(name: str, label_key: Tuple[Tuple[str, str], ...]) -> str:
    """``name`` or ``name{k="v",k2="v2"}`` (Prometheus exposition form)."""
    if not label_key:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in label_key)
    return f"{name}{{{inner}}}"


def split_name(rendered: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Inverse of :func:`render_name` (for the Prometheus parser)."""
    if "{" not in rendered:
        return rendered, ()
    name, _, rest = rendered.partition("{")
    rest = rest.rstrip("}")
    labels = []
    for part in rest.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        labels.append((k.strip(), v.strip().strip('"')))
    return name, tuple(sorted(labels))


class MetricsRegistry:
    """One lock, four metric families.  Safe for any number of writer
    threads; snapshots are consistent cuts (taken under the lock)."""

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.lock = threading.RLock()
        self._buckets = tuple(sorted(float(b) for b in buckets))
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # rendered name -> [bucket counts..., +Inf count], sum, count
        self._hists: Dict[str, list] = {}
        # event name -> [calls, total, min, max] (profiler aggregate)
        self._timings: Dict[str, list] = {}
        # optional (ts_us, rendered_name, value) counter/gauge samples for
        # the chrome-trace exporter ("ph": "C" events); enabled by the
        # profiler session so steady-state production pays nothing
        self._samples: Optional[list] = None
        self._samples_t0 = 0.0
        self._samples_cap = 200_000

    # ------------------------------------------------------------------
    # writing
    # ------------------------------------------------------------------

    def inc(self, name: str, value: float = 1,
            labels: Optional[dict] = None) -> float:
        """Add ``value`` to a counter; returns the new total."""
        key = render_name(name, _label_key(labels))
        with self.lock:
            new = self._counters.get(key, 0) + value
            self._counters[key] = new
            self._sample(key, new)
        return new

    def set_gauge(self, name: str, value: float,
                  labels: Optional[dict] = None) -> None:
        key = render_name(name, _label_key(labels))
        with self.lock:
            self._gauges[key] = value
            self._sample(key, value)

    def observe(self, name: str, value: float,
                labels: Optional[dict] = None) -> None:
        """One histogram observation."""
        key = render_name(name, _label_key(labels))
        v = float(value)
        with self.lock:
            h = self._hists.get(key)
            if h is None:
                h = [[0] * (len(self._buckets) + 1), 0.0, 0]
                self._hists[key] = h
            counts, _, _ = h
            for i, ub in enumerate(self._buckets):
                if v <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            h[1] += v
            h[2] += 1

    def record_timing(self, name: str, seconds: float) -> None:
        """Profiler-style [calls, total, min, max] aggregate."""
        s = float(seconds)
        with self.lock:
            e = self._timings.get(name)
            if e is None:
                self._timings[name] = [1, s, s, s]
            else:
                e[0] += 1
                e[1] += s
                e[2] = min(e[2], s)
                e[3] = max(e[3], s)

    def _sample(self, key: str, value) -> None:
        # caller holds self.lock
        if self._samples is None or len(self._samples) >= self._samples_cap:
            return
        ts = (time.perf_counter() - self._samples_t0) * 1e6
        self._samples.append({"name": key, "ts": ts, "value": value})

    # ------------------------------------------------------------------
    # sampling control (profiler session hooks)
    # ------------------------------------------------------------------

    def start_sampling(self, t0: Optional[float] = None) -> None:
        """Begin recording per-change counter samples (chrome-trace "C"
        events), timestamped relative to ``t0`` (perf_counter)."""
        with self.lock:
            self._samples = []
            self._samples_t0 = time.perf_counter() if t0 is None else t0

    def stop_sampling(self) -> list:
        with self.lock:
            out, self._samples = self._samples or [], None
        return out

    # ------------------------------------------------------------------
    # reading
    # ------------------------------------------------------------------

    def flat(self) -> Dict[str, float]:
        """Counters + gauges as one rendered-name -> value dict (the
        ``fluid.profiler.counters()`` compatibility view)."""
        with self.lock:
            out = dict(self._counters)
            out.update(self._gauges)
        return out

    def timings(self) -> Dict[str, tuple]:
        with self.lock:
            return {k: tuple(v) for k, v in self._timings.items()}

    def snapshot(self) -> dict:
        """Structured consistent cut: counters / gauges / histograms
        (each histogram: bucket bounds, cumulative counts, sum, count)."""
        with self.lock:
            hists = {k: {"buckets": list(self._buckets),
                         "counts": list(h[0]),
                         "sum": h[1], "count": h[2]}
                     for k, h in self._hists.items()}
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "histograms": hists}

    def clear(self, timings_only: bool = False) -> None:
        with self.lock:
            self._timings.clear()
            if not timings_only:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
