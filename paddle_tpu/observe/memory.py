"""Device-memory observability: compiled truth + the live-buffer ledger.

PR 9 answered *where the time went*; this module answers *where the HBM
went* — the other half of TPU production observability.  An OOM used to
surface as an opaque XLA RESOURCE_EXHAUSTED with no gauge, event or
pre-flight warning; now three tiers cover it:

 1. **Compiled truth** (:func:`memory_stats` / :func:`note_compiled_memory`)
    — ``compiled.memory_analysis()`` read at the existing AOT-lower points
    (the PR 7 ``ShardedWindowRunner``, the PR 9 traced single-device
    lowering, ``ServingEngine.warmup()``) into always-on gauges
    ``memory.peak_bytes{mesh=...}`` / ``memory.argument_bytes`` /
    ``memory.output_bytes`` / ``memory.temp_bytes`` /
    ``memory.generated_code_bytes`` plus one ``memory.profile`` run event
    per executable.  The stats also land in the compile-cache manifest, so
    a warm start re-reports memory WITHOUT re-lowering
    (``compile_cache._Probe.finish``).

 2. **Pre-flight estimate** — ``paddle_tpu.analysis.memcheck`` (AN5xx):
    the static twin of this module, cross-checked against
    :func:`memory_stats` in tests the way AN204's collective estimate is
    cross-checked against ``spmd.collective_bytes``.

 3. **Live-buffer ledger** (:class:`LiveBufferLedger`) — host-side
    tracking of live ``jax.Array`` bytes per (scope, mesh): the executor
    paths report their scope's device residency after each state commit,
    the prefetcher reports its staged-window bytes, and the ledger turns
    them into ``memory.live_bytes{scope=,mesh=}`` /
    ``memory.live_high_water_bytes`` gauges, ``memory.watermark`` run
    events at window boundaries (gated by ``PADDLE_MEM_WATERMARK``), a
    ``memory.over_budget`` event when residency exceeds
    ``PADDLE_MEM_BUDGET_MB``, and an SLO-watchdog feed
    (``memory.live_bytes``) so monotonic growth across windows or elastic
    generations breaches like a slow step — leak detection with the same
    median+MAD machinery that catches latency regressions.
    ``PADDLE_FAULT_MEM_PRESSURE`` synthesizes that growth
    deterministically (``fluid.fault.mem_pressure_bytes``).

Chrome-trace integration: watermark events carry a ``counters`` field the
exporter renders as ``"ph": "C"`` counter tracks, and the gauges are
sampled by the profiler session (``registry.start_sampling``), so both
``python -m paddle_tpu.observe export`` and ``tools/timeline.py`` show
HBM residency alongside the span timeline.

Costs: reading ``memory_analysis()`` needs a *compiled* executable.  The
sharded window runner already AOT-compiles (free); the traced
single-device window pays one extra backend compile the first time a
window entry is lowered under tracing (the persistent backend cache
dedupes it when enabled); warmup is the precompile path by definition.
The ledger is a sum of ``nbytes`` over scope entries per window — host
arithmetic, no device sync.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "memory_stats", "note_compiled_memory", "LiveBufferLedger", "ledger",
    "scope_live_bytes", "note_scope_live", "adjust_staged", "reset",
]

#: gauge names published by note_compiled_memory, in stat-key order
COMPILED_GAUGES = (
    ("peak_bytes", "memory.peak_bytes"),
    ("argument_bytes", "memory.argument_bytes"),
    ("output_bytes", "memory.output_bytes"),
    ("temp_bytes", "memory.temp_bytes"),
    ("generated_code_bytes", "memory.generated_code_bytes"),
)


def memory_stats(compiled) -> Optional[dict]:
    """``memory_analysis()`` of a jax ``Compiled`` as a plain dict:
    ``{"peak_bytes", "argument_bytes", "output_bytes", "temp_bytes",
    "generated_code_bytes", "alias_bytes"}`` — per-device bytes of the
    executable.  ``peak_bytes`` is the standard buffer-assignment
    approximation ``argument + output - alias + temp + generated_code``
    (donated outputs alias their argument buffers and must not double
    count).  None when the backend exposes no memory analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None

    def _get(attr) -> int:
        try:
            return int(getattr(ma, attr, 0) or 0)
        except (TypeError, ValueError):
            return 0

    arg = _get("argument_size_in_bytes")
    out = _get("output_size_in_bytes")
    temp = _get("temp_size_in_bytes")
    code = _get("generated_code_size_in_bytes")
    alias = _get("alias_size_in_bytes")
    if arg + out + temp + code <= 0:
        return None
    peak = max(arg + out - alias + temp + code, arg, temp)
    return {"peak_bytes": peak, "argument_bytes": arg, "output_bytes": out,
            "temp_bytes": temp, "generated_code_bytes": code,
            "alias_bytes": alias}


def note_compiled_memory(stats: Optional[dict], mesh: Optional[str] = None,
                         kind: Optional[str] = None,
                         n_steps: Optional[int] = None,
                         cached: bool = False) -> None:
    """Publish one executable's memory stats: the ``memory.*`` gauge
    family (mesh-labeled on sharded runs) plus one ``memory.profile`` run
    event.  ``cached=True`` marks a warm-start re-report from a
    compile-cache manifest (no lowering happened).  Never raises."""
    if not stats:
        return
    try:
        from . import emit, registry

        reg = registry()
        labels = {"mesh": mesh} if mesh else None
        for key, gauge in COMPILED_GAUGES:
            v = stats.get(key)
            if isinstance(v, (int, float)):
                reg.set_gauge(gauge, float(v), labels=labels)
        emit("memory.profile", mesh=mesh, kind=kind, n_steps=n_steps,
             cached=bool(cached) or None,
             **{k: stats.get(k) for k, _ in COMPILED_GAUGES},
             alias_bytes=stats.get("alias_bytes"))
    except Exception:
        pass  # accounting must never fail the run it measures


# ---------------------------------------------------------------------------
# live-buffer ledger
# ---------------------------------------------------------------------------


def scope_live_bytes(scope) -> int:
    """Total bytes of device-resident ``jax.Array`` values a Scope holds
    (logical/global bytes; divide by the shard count for per-device).
    Host numpy state counts zero — it is not HBM."""
    import jax

    total = 0
    for val in list(scope._values.values()):
        if isinstance(val, jax.Array):
            try:
                total += int(val.nbytes)
            except Exception:
                pass
    return total


class LiveBufferLedger:
    """Thread-safe live/high-water accounting per (scope label, mesh).

    One process-wide instance (``ledger()``); writers are the executor
    window paths (scope residency after each state commit), the device
    prefetcher (staged-window bytes), and anything else holding device
    buffers worth attributing.  Every update refreshes the gauges; the
    TOTAL across keys feeds the SLO watchdog and the budget check."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live: Dict[Tuple[str, str], int] = {}
        self._high: Dict[Tuple[str, str], int] = {}

    def _key(self, scope_label: str, mesh: Optional[str]):
        return (str(scope_label), mesh or "")

    def update(self, scope_label: str, nbytes: int,
               mesh: Optional[str] = None, step: Optional[int] = None,
               emit_event: bool = False) -> int:
        """Set one key's live bytes (absolute).  Returns the process-total
        live bytes after the update (fault mem-pressure included)."""
        nbytes = max(0, int(nbytes))
        key = self._key(scope_label, mesh)
        with self._lock:
            self._live[key] = nbytes
            high = max(self._high.get(key, 0), nbytes)
            self._high[key] = high
            total = sum(self._live.values())
        try:
            from ..fluid import fault as _fault

            total += _fault.mem_pressure_bytes()
        except Exception:
            pass
        self._publish(key, nbytes, high, total, step, emit_event)
        return total

    def adjust(self, scope_label: str, delta: int,
               mesh: Optional[str] = None) -> int:
        """Relative update (the prefetcher's +staged/-consumed path)."""
        key = self._key(scope_label, mesh)
        with self._lock:
            cur = max(0, self._live.get(key, 0) + int(delta))
        return self.update(scope_label, cur, mesh=mesh)

    def live(self, scope_label: str, mesh: Optional[str] = None) -> int:
        with self._lock:
            return self._live.get(self._key(scope_label, mesh), 0)

    def high_water(self, scope_label: str,
                   mesh: Optional[str] = None) -> int:
        with self._lock:
            return self._high.get(self._key(scope_label, mesh), 0)

    def _publish(self, key, nbytes, high, total, step, emit_event) -> None:
        try:
            from . import emit, registry
            from .watchdog import observe_value
            from ..fluid import envcontract

            scope_label, mesh = key
            labels = {"scope": scope_label}
            if mesh:
                labels["mesh"] = mesh
            reg = registry()
            reg.set_gauge("memory.live_bytes", float(nbytes), labels=labels)
            reg.set_gauge("memory.live_high_water_bytes", float(high),
                          labels=labels)
            reg.set_gauge("memory.live_total_bytes", float(total))
            # leak detection: the TOTAL feeds the watchdog, so growth in
            # any scope (or an injected PADDLE_FAULT_MEM_PRESSURE ramp)
            # breaches like a slow step
            observe_value("memory.live_bytes", float(total), step=step,
                          scope=scope_label)
            budget_mb = envcontract.get("PADDLE_MEM_BUDGET_MB")
            over = (budget_mb is not None
                    and total > float(budget_mb) * (1 << 20))
            if over:
                reg.inc("memory.over_budget")
            if emit_event and envcontract.get("PADDLE_MEM_WATERMARK"):
                from .registry import render_name

                emit("memory.watermark", scope=scope_label,
                     mesh=mesh or None, live_bytes=int(nbytes),
                     high_water_bytes=int(high), total_bytes=int(total),
                     counters={render_name(
                         "memory.live_bytes",
                         tuple(sorted(labels.items()))): int(nbytes)})
            if over:
                emit("memory.over_budget", scope=scope_label,
                     mesh=mesh or None, total_bytes=int(total),
                     budget_mb=budget_mb)
        except Exception:
            pass

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._high.clear()


_ledger = LiveBufferLedger()


def ledger() -> LiveBufferLedger:
    """THE process live-buffer ledger."""
    return _ledger


def note_scope_live(scope, scope_label: str = "train",
                    mesh: Optional[str] = None, step: Optional[int] = None,
                    emit_event: bool = True) -> int:
    """Report a Scope's current device residency to the ledger — the
    executor window paths call this right after committing new state.
    ``emit_event=False`` is the per-step path's quiet form (gauges only,
    no watermark record per step).  Never raises; returns total bytes."""
    try:
        return _ledger.update(scope_label, scope_live_bytes(scope),
                              mesh=mesh, step=step, emit_event=emit_event)
    except Exception:
        return 0


def adjust_staged(delta: int, mesh: Optional[str] = None) -> None:
    """Prefetcher hook: add (staged) / subtract (consumed) window bytes
    under the ``prefetch`` scope label."""
    try:
        _ledger.adjust("prefetch", delta, mesh=mesh)
    except Exception:
        pass


def reset() -> None:
    """Clear ledger state (test-harness hook, via ``observe.reset``)."""
    _ledger.clear()
