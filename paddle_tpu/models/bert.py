"""BERT-style masked-LM pretraining model (BASELINE config #5 "ERNIE /
BERT-base pretraining (DistributeTranspiler SPMD on pod)").

The reference era predates an in-tree BERT; the config names the
*capability*: a deep bidirectional transformer encoder pretrained with
masked-LM + next-sentence-prediction, trained data/model-parallel on the
pod.  Architecture follows Devlin et al.: learned position + token-type
embeddings, post-LN encoder blocks (reused from models/transformer.py),
an MLM head that gathers the masked positions (so the [B*T, V] logits
matrix never materializes — only [n_mask, V]) and an NSP head on the [CLS]
vector.  All parameters are plain fluid layers, so ParallelExecutor /
ShardedTrainStep shard it like any other program (dp / mp / ZeRO-1).
"""

from __future__ import annotations

import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr
from .transformer import (Config, _ffn, _multi_head_attention, _padding_bias,
                          _postprocess)


class BertConfig:
    def __init__(self, name, vocab_size=30522, d_model=768, d_inner=3072,
                 n_head=12, n_layer=12, type_vocab_size=2, max_len=512,
                 dropout=0.1, ring_attention=False, stacked=False,
                 n_microbatches=4, recompute=False, flash_attention=None):
        self.name = name
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.type_vocab_size = type_vocab_size
        self.max_len = max_len
        self.dropout = dropout
        # ring_attention=True routes every encoder attention through
        # layers.ring_attention: long sequences shard over an "sp" mesh
        # axis (models/transformer.Config.ring_attention semantics)
        self.ring_attention = ring_attention
        # stacked=True builds the encoder as ONE mesh-aware layer-stack op
        # (layers.transformer_encoder_stack): pipeline over "pp", Megatron
        # TP over "mp", ring attention over "sp" — same semantics as
        # models/transformer.Config.stacked; recompute adds per-layer
        # jax.checkpoint for long-sequence memory
        self.stacked = stacked
        self.n_microbatches = n_microbatches
        self.recompute = recompute
        # flash_attention: models/transformer.Config.flash_attention
        # semantics (True/False/None-auto Pallas streamed attention)
        self.flash_attention = flash_attention


def base_config():
    return BertConfig("base")


def tiny_config():
    return BertConfig("tiny", vocab_size=500, d_model=64, d_inner=128,
                      n_head=4, n_layer=2, max_len=64, dropout=0.0)


def _bert_embed(ids, type_ids, cfg, seq_len):
    word = layers.embedding(
        ids, size=[cfg.vocab_size, cfg.d_model],
        param_attr=ParamAttr(name="bert_word_emb"))
    pos_ids = layers.assign(np.arange(seq_len, dtype=np.int64))
    pos = layers.embedding(
        pos_ids, size=[cfg.max_len, cfg.d_model],
        param_attr=ParamAttr(name="bert_pos_emb"))
    typ = layers.embedding(
        type_ids, size=[cfg.type_vocab_size, cfg.d_model],
        param_attr=ParamAttr(name="bert_type_emb"))
    out = layers.elementwise_add(layers.elementwise_add(word, typ), pos)
    out = layers.layer_norm(out, begin_norm_axis=2)
    if cfg.dropout:
        out = layers.dropout(out, dropout_prob=cfg.dropout)
    return out


def encoder_stack(emb, pad_bias, cfg):
    if getattr(cfg, "stacked", False):
        return layers.transformer_encoder_stack(
            emb, bias=pad_bias, n_layer=cfg.n_layer, n_head=cfg.n_head,
            d_inner=cfg.d_inner, dropout=cfg.dropout,
            n_microbatches=getattr(cfg, "n_microbatches", 4),
            recompute=getattr(cfg, "recompute", False),
            flash=getattr(cfg, "flash_attention", None))
    enc = emb
    for i in range(cfg.n_layer):
        attn = _multi_head_attention(
            enc, enc, enc, pad_bias, cfg.d_model, cfg.n_head, cfg.dropout,
            prefix=f"bert{i}_self",
            use_ring=getattr(cfg, "ring_attention", False),
            flash=getattr(cfg, "flash_attention", None))
        enc = _postprocess(enc, attn, cfg.dropout)
        ff = _ffn(enc, cfg.d_inner, cfg.d_model, prefix=f"bert{i}")
        enc = _postprocess(enc, ff, cfg.dropout)
    return enc


def forward(cfg, seq_len, n_mask):
    """Build the pretraining graph; returns (inputs..., losses, logits).

    Feeds:
      src_ids    int64 [B, seq_len]      token ids (0 = pad)
      type_ids   int64 [B, seq_len]      segment A/B ids
      mask_pos   int64 [B*n_mask]        FLAT positions into [B*T] rows
      mask_label int64 [B*n_mask, 1]     original token at each masked slot
      nsp_label  int64 [B, 1]            is-next-sentence
    """
    src_ids = layers.data(name="src_ids", shape=[seq_len], dtype="int64")
    type_ids = layers.data(name="type_ids", shape=[seq_len], dtype="int64")
    mask_pos = layers.data(name="mask_pos", shape=[1], dtype="int64")
    mask_label = layers.data(name="mask_label", shape=[1], dtype="int64")
    nsp_label = layers.data(name="nsp_label", shape=[1], dtype="int64")

    emb = _bert_embed(src_ids, type_ids, cfg, seq_len)
    pad_bias = _padding_bias(src_ids, seq_len)
    enc = encoder_stack(emb, pad_bias, cfg)   # [B, T, D]

    # MLM head: gather ONLY the masked rows before projecting to the vocab
    # (ref-era models project all B*T rows; gathering first keeps the big
    # [*, V] matmul at n_mask rows — the standard BERT trick, MXU-friendly)
    flat = layers.reshape(enc, shape=[-1, cfg.d_model])     # [B*T, D]
    masked = layers.gather(flat, mask_pos)                  # [B*n_mask, D]
    masked = layers.fc(masked, cfg.d_model, act="relu",
                       param_attr=ParamAttr(name="mlm_transform_w"))
    masked = layers.layer_norm(masked, begin_norm_axis=1)
    mlm_logits = layers.fc(masked, cfg.vocab_size,
                           param_attr=ParamAttr(name="mlm_out_w"))
    mlm_prob = layers.softmax(mlm_logits)
    mlm_loss = layers.mean(layers.cross_entropy(mlm_prob, mask_label))

    # NSP head on the [CLS] (position 0) vector
    cls = layers.slice(enc, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, shape=[-1, cfg.d_model])
    pooled = layers.fc(cls, cfg.d_model, act="tanh",
                       param_attr=ParamAttr(name="bert_pooler_w"))
    nsp_prob = layers.fc(pooled, 2, act="softmax",
                         param_attr=ParamAttr(name="nsp_out_w"))
    nsp_loss = layers.mean(layers.cross_entropy(nsp_prob, nsp_label))

    total = layers.elementwise_add(mlm_loss, nsp_loss)
    return (src_ids, type_ids, mask_pos, mask_label, nsp_label,
            total, mlm_loss, nsp_loss, mlm_prob)


def build(cfg=None, seq_len=128, n_mask=20, lr=1e-4):
    cfg = cfg or base_config()
    outs = forward(cfg, seq_len, n_mask)
    total = outs[5]
    fluid.optimizer.Adam(learning_rate=lr).minimize(total)
    return outs


def synthetic_batch(cfg, batch, seq_len, n_mask, rng):
    """Deterministic learnable pretraining batch: each sequence is a Markov
    chain (token i -> perm[i] w.p. 0.9), so MLM is genuinely predictable
    from context; NSP label = whether segment B continues the chain."""
    perm = np.random.RandomState(1234).permutation(cfg.vocab_size - 10) + 10
    ids = np.zeros((batch, seq_len), np.int64)
    typ = np.zeros((batch, seq_len), np.int64)
    nsp = np.zeros((batch, 1), np.int64)
    half = seq_len // 2
    for b in range(batch):
        w = int(rng.randint(10, cfg.vocab_size))
        for t in range(seq_len):
            ids[b, t] = w
            nxt = perm[(w - 10) % len(perm)]
            w = int(nxt) if rng.uniform() < 0.9 \
                else int(rng.randint(10, cfg.vocab_size))
        typ[b, half:] = 1
        if rng.uniform() < 0.5:  # corrupt segment B -> not-next
            ids[b, half:] = rng.randint(10, cfg.vocab_size,
                                        size=seq_len - half)
            nsp[b, 0] = 0
        else:
            nsp[b, 0] = 1
    # mask n_mask positions per sequence (avoid position 0 = CLS slot)
    mask_pos = np.zeros((batch * n_mask,), np.int64)
    mask_label = np.zeros((batch * n_mask, 1), np.int64)
    for b in range(batch):
        pos = rng.choice(np.arange(1, seq_len), size=n_mask, replace=False)
        for j, p in enumerate(pos):
            mask_pos[b * n_mask + j] = b * seq_len + p
            mask_label[b * n_mask + j, 0] = ids[b, p]
            ids[b, p] = 1  # [MASK] id
    return {"src_ids": ids, "type_ids": typ, "mask_pos": mask_pos,
            "mask_label": mask_label, "nsp_label": nsp}
