"""Transformer encoder-decoder (the second driver metric: Transformer-base
tokens/sec/chip).

Functional contract follows the reference's Transformer test model
(python/paddle/fluid/tests/unittests/transformer_model.py: multi-head
attention, position encoding, pre/post-process residual+norm+dropout,
label-smoothed softmax CE) but the design is TPU-first rather than a
translation: everything is static-shape dense [batch, seq_len] tensors, the
causal and padding masks are additive biases broadcast into the pre-softmax
logits (no LoD, no data-dependent shapes), and the whole step traces into a
single XLA program whose attention/FFN matmuls tile onto the MXU.
"""

from __future__ import annotations

import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.initializer import NumpyArrayInitializer
from ..fluid.param_attr import ParamAttr

_NEG_INF = -1e9


class Config:
    def __init__(self, name, src_vocab_size, tgt_vocab_size, d_model,
                 d_inner, n_head, n_layer, dropout=0.1, label_smooth=0.1,
                 moe_experts=0, moe_top_k=2, moe_aux_weight=1e-2,
                 stacked=False, ring_attention=False, n_microbatches=4,
                 recompute=False, flash_attention=None):
        self.name = name
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout
        self.label_smooth = label_smooth
        # moe_experts > 0 replaces every FFN with an expert-parallel MoE
        # layer (Switch-style; experts shard over an "ep" mesh axis)
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_aux_weight = moe_aux_weight
        # stacked=True builds the encoder/decoder as ONE mesh-aware
        # layer-stack op with [L, ...] params (layers.transformer_*_stack):
        # pipeline-parallel over "pp", Megatron-TP over "mp", ring-
        # attention over "sp" — the pipeline-capable flagship build.
        # Residual dropout only in this mode (see transformer_stack).
        self.stacked = stacked
        # ring_attention=True keeps the per-layer graph but routes every
        # attention through layers.ring_attention, so the UNstacked model
        # sequence-parallelizes over an "sp" mesh axis too.  Attention-
        # probability dropout is skipped in this mode (the [T, T] matrix
        # never materializes under the ring).
        self.ring_attention = ring_attention
        # flash_attention: True routes every attention through the Pallas
        # streamed kernel (fwd + bwd, ops/pallas_flash.py), False forbids
        # it, None = auto (on for TPU backends; PADDLE_TPU_FLASH
        # overrides).  Attention-probability dropout is skipped on the
        # flash path (the [T, T] matrix never materializes), like ring.
        self.flash_attention = flash_attention
        self.n_microbatches = n_microbatches
        # recompute=True (stacked mode) wraps each layer in
        # jax.checkpoint: backward rematerializes activations layer by
        # layer — peak memory O(T*D) instead of O(L*T*D) for long
        # sequences at the cost of one extra forward
        self.recompute = recompute


def base_config():
    """Transformer-base (Vaswani et al.): d_model 512, 8 heads, 6 layers."""
    return Config("base", src_vocab_size=30000, tgt_vocab_size=30000,
                  d_model=512, d_inner=2048, n_head=8, n_layer=6)


def tiny_config():
    """CPU-test scale."""
    return Config("tiny", src_vocab_size=1000, tgt_vocab_size=1000,
                  d_model=64, d_inner=128, n_head=4, n_layer=2)


def _position_encoding(max_len, d_model):
    """Sinusoid table [max_len, d_model] (Vaswani et al. eq. 5)."""
    pos = np.arange(max_len, dtype=np.float64)[:, None]
    div = np.exp(np.arange(0, d_model, 2, dtype=np.float64)
                 * -(np.log(10000.0) / d_model))
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    return table


def _shared_causal_bias(lq, lk):
    """One additive triu causal mask per (program, shape) — every decoder
    layer shares the same constant var instead of re-materializing it."""
    from .. import fluid as _fluid

    prog = _fluid.default_main_program()
    cache = getattr(prog, "_causal_bias_cache", None)
    if cache is None:
        cache = prog._causal_bias_cache = {}
    var = cache.get((lq, lk))
    if var is None:
        causal_np = np.triu(np.full((lq, lk), _NEG_INF, np.float32), k=1)
        var = cache[(lq, lk)] = layers.assign(causal_np)
    return var


def _postprocess(prev, out, dropout):
    """Residual add + layer norm (+ dropout on the sublayer output)."""
    if dropout:
        out = layers.dropout(out, dropout_prob=dropout)
    return layers.layer_norm(layers.elementwise_add(prev, out),
                             begin_norm_axis=2)


def _multi_head_attention(q_in, k_in, v_in, bias, d_model, n_head,
                          dropout, prefix, causal=False, use_ring=False,
                          flash=None):
    """[b, lq, d] x [b, lk, d] -> [b, lq, d]; bias broadcasts into the
    [b, h, lq, lk] logits (None, [lq, lk] causal, or [b, 1, 1, lk] padding).

    use_ring=True routes the attention through layers.ring_attention
    (sequence-parallel over an "sp" mesh axis, mathematically identical
    single-device); the causal mask is then expressed via the op's
    ``causal`` flag and ``bias`` must be a key-position padding bias
    ([b, 1, 1, lk]) or None — and attention-probability dropout is skipped
    (the ring never materializes the probability matrix)."""
    lq, lk = q_in.shape[1], k_in.shape[1]
    d_k = d_model // n_head
    q = layers.fc(q_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_q_w"))
    k = layers.fc(k_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_k_w"))
    v = layers.fc(v_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_v_w"))
    # [b, l, d] -> [b, h, l, d_k]
    q = layers.transpose(layers.reshape(q, [-1, lq, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    k = layers.transpose(layers.reshape(k, [-1, lk, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    v = layers.transpose(layers.reshape(v, [-1, lk, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    from ..ops.attention_ops import _flash_decision
    if use_ring or flash or (flash is None and _flash_decision()):
        # the fused attention op: executor picks ring (sp mesh axis) /
        # Pallas flash / XLA full softmax; prob-dropout is skipped.
        # flash=None auto-routes here when the backend would take the
        # Pallas path (TPU, PADDLE_TPU_FLASH honored) so the Config
        # docstring's "None = auto" holds for dense builds too
        ctx = layers.ring_attention(q, k, v, causal=causal,
                                    scale=d_k ** -0.5, bias=bias,
                                    flash=flash)
    else:
        logits = layers.matmul(layers.scale(q, scale=d_k ** -0.5), k,
                               transpose_y=True)
        if causal:
            # one shared [lq, lk] mask var per program+shape: layers would
            # otherwise each carry their own identical triu constant
            logits = layers.elementwise_add(logits,
                                            _shared_causal_bias(lq, lk))
        if bias is not None:
            logits = layers.elementwise_add(logits, bias)
        weights = layers.softmax(logits)
        if dropout:
            weights = layers.dropout(weights, dropout_prob=dropout)
        ctx = layers.matmul(weights, v)                  # [b, h, lq, d_k]
    ctx = layers.reshape(layers.transpose(ctx, perm=[0, 2, 1, 3]),
                         [-1, lq, d_model])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=ParamAttr(name=f"{prefix}_o_w"))


def _ffn(x, d_inner, d_model, prefix, cfg=None, aux_losses=None):
    if cfg is not None and cfg.moe_experts:
        out, aux = layers.moe_ffn(x, num_experts=cfg.moe_experts,
                                  hidden_size=d_inner,
                                  top_k=cfg.moe_top_k)
        if aux_losses is not None:
            aux_losses.append(aux)
        return out
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu",
                  param_attr=ParamAttr(name=f"{prefix}_ffn1_w"))
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(name=f"{prefix}_ffn2_w"))


def _embed(word, vocab_size, seq_len, cfg, name):
    emb = layers.embedding(
        word, size=[vocab_size, cfg.d_model],
        param_attr=ParamAttr(
            name=f"{name}_emb",
            initializer=fluid.initializer.NormalInitializer(
                0.0, cfg.d_model ** -0.5)))
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    pos = layers.create_parameter(
        shape=[seq_len, cfg.d_model], dtype="float32",
        attr=ParamAttr(name=f"{name}_pos_enc",
                       initializer=NumpyArrayInitializer(
                           _position_encoding(seq_len, cfg.d_model)),
                       trainable=False))
    out = layers.elementwise_add(emb, pos)
    if cfg.dropout:
        out = layers.dropout(out, dropout_prob=cfg.dropout)
    return out


def _padding_bias(word, seq_len):
    """[b, len] int ids -> additive bias [b, 1, 1, len]: NEG_INF at pad(0)."""
    zeros = layers.fill_constant_batch_size_like(
        word, shape=[-1, seq_len], dtype="int64", value=0)
    is_pad = layers.cast(layers.equal(word, zeros), "float32")
    bias = layers.scale(is_pad, scale=_NEG_INF)
    return layers.reshape(bias, [-1, 1, 1, seq_len])


def moe_config():
    """Switch-Transformer-style MoE variant of the tiny config (expert
    parallelism demo/test model; SURVEY.md §2.6: MoE/EP beyond-reference)."""
    c = tiny_config()
    c.name = "moe_tiny"
    c.moe_experts = 4
    return c


def encoder(src_word, cfg, src_len, aux_losses=None):
    enc = _embed(src_word, cfg.src_vocab_size, src_len, cfg, "src")
    src_bias = _padding_bias(src_word, src_len)
    if cfg.stacked:
        enc = layers.transformer_encoder_stack(
            enc, bias=src_bias, n_layer=cfg.n_layer, n_head=cfg.n_head,
            d_inner=cfg.d_inner, dropout=cfg.dropout,
            n_microbatches=cfg.n_microbatches,
            recompute=getattr(cfg, "recompute", False),
            flash=getattr(cfg, "flash_attention", None))
        return enc, src_bias
    for i in range(cfg.n_layer):
        attn = _multi_head_attention(
            enc, enc, enc, src_bias, cfg.d_model, cfg.n_head, cfg.dropout,
            prefix=f"enc{i}_self", use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        enc = _postprocess(enc, attn, cfg.dropout)
        ff = _ffn(enc, cfg.d_inner, cfg.d_model, prefix=f"enc{i}",
                  cfg=cfg, aux_losses=aux_losses)
        enc = _postprocess(enc, ff, cfg.dropout)
    return enc, src_bias


def decoder(tgt_word, enc_out, src_bias, cfg, tgt_len, aux_losses=None):
    dec = _embed(tgt_word, cfg.tgt_vocab_size, tgt_len, cfg, "tgt")
    if cfg.stacked:
        dec = layers.transformer_decoder_stack(
            dec, enc_out, src_bias=src_bias, n_layer=cfg.n_layer,
            n_head=cfg.n_head, d_inner=cfg.d_inner, dropout=cfg.dropout,
            n_microbatches=cfg.n_microbatches,
            recompute=getattr(cfg, "recompute", False),
            flash=getattr(cfg, "flash_attention", None))
        return layers.fc(dec, cfg.tgt_vocab_size, num_flatten_dims=2,
                         param_attr=ParamAttr(name="out_proj_w"))
    for i in range(cfg.n_layer):
        self_attn = _multi_head_attention(
            dec, dec, dec, None, cfg.d_model, cfg.n_head, cfg.dropout,
            prefix=f"dec{i}_self", causal=True, use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        dec = _postprocess(dec, self_attn, cfg.dropout)
        cross = _multi_head_attention(
            dec, enc_out, enc_out, src_bias, cfg.d_model, cfg.n_head,
            cfg.dropout, prefix=f"dec{i}_cross", use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        dec = _postprocess(dec, cross, cfg.dropout)
        ff = _ffn(dec, cfg.d_inner, cfg.d_model, prefix=f"dec{i}",
                  cfg=cfg, aux_losses=aux_losses)
        dec = _postprocess(dec, ff, cfg.dropout)
    return layers.fc(dec, cfg.tgt_vocab_size, num_flatten_dims=2,
                     param_attr=ParamAttr(name="out_proj_w"))


def forward(cfg, src_len, tgt_len):
    """Build data layers + logits + label-smoothed CE loss.  Returns
    (src_word, tgt_word, lbl_word, avg_cost, logits)."""
    src_word = layers.data(name="src_word", shape=[src_len], dtype="int64")
    tgt_word = layers.data(name="tgt_word", shape=[tgt_len], dtype="int64")
    lbl_word = layers.data(name="lbl_word", shape=[tgt_len, 1], dtype="int64")

    aux_losses = []
    enc_out, src_bias = encoder(src_word, cfg, src_len, aux_losses)
    logits = decoder(tgt_word, enc_out, src_bias, cfg, tgt_len, aux_losses)

    if cfg.label_smooth:
        hot = layers.one_hot(lbl_word, cfg.tgt_vocab_size)
        smooth = layers.label_smooth(hot, epsilon=cfg.label_smooth)
        cost = layers.softmax_with_cross_entropy(logits, smooth,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(logits, lbl_word)
    # mask loss at pad targets so padding doesn't dilute the objective
    zeros = layers.fill_constant_batch_size_like(
        lbl_word, shape=[-1, tgt_len, 1], dtype="int64", value=0)
    non_pad = layers.cast(
        layers.logical_not(layers.equal(lbl_word, zeros)), "float32")
    cost = layers.elementwise_mul(cost, non_pad)
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(cost),
        layers.elementwise_add(layers.reduce_sum(non_pad),
                               layers.fill_constant([1], "float32", 1e-8)))
    for aux in aux_losses:  # Switch load-balancing losses (MoE configs)
        avg_cost = layers.elementwise_add(
            avg_cost, layers.scale(aux, scale=cfg.moe_aux_weight))
    return src_word, tgt_word, lbl_word, avg_cost, logits


# ---------------------------------------------------------------------------
# Step-form decode (ISSUE 15): slot-based KV cache, one compiled decode step
# ---------------------------------------------------------------------------


def decode_lm_config():
    """Decoder-only LM at CPU-test scale for the continuous-batching
    serving path (``serving.decode.DecodeEngine``): self-attention only,
    single head, no dropout — the deterministic greedy-decode model the
    convoy/bitwise oracles run against."""
    return Config("decode_lm", src_vocab_size=64, tgt_vocab_size=64,
                  d_model=16, d_inner=32, n_head=1, n_layer=2,
                  dropout=0.0, label_smooth=0.0)


class DecodeModel:
    """Step-form decoder-only transformer LM: the decode programs the
    continuous-batching engine drives token-by-token.

    Three program families, all sharing parameters AND per-layer KV
    caches BY NAME through one scope:

     - ``startup``: initializes every weight plus the per-layer
       ``dlm{i}_cache_k/v`` caches — persistable ``[max_slots, max_len,
       d_model]`` zeros that live device-resident across dispatches (the
       slot-based KV cache);
     - ``step_program``: ONE fixed-shape program over ALL slots — embed
       the last token, project q/k/v, ``kv_cache_update`` this tick's
       K/V at each slot's write position, attend over the cache row
       under a host-fed ``-inf`` validity bias, project logits,
       ``token_select`` greedily.  Fixed ``[max_slots, ...]`` shapes ⇒
       exactly one executable regardless of which slots are live;
     - ``prefill_program(plen)``: one program per prompt-length bucket
       (single request): local causal attention over the prompt window
       and a ``kv_cache_update`` scatter of the whole K/V prefix into
       the request's slot at position 0.  No logits — the first decode
       tick re-derives position ``len-1`` (same weights, same token ⇒
       bit-identical K/V) and emits the first token, so the executable
       set stays small.

    Bitwise sequential-equivalence contract: every op is row-independent
    over the slot dim and masked cache positions contribute EXACTLY zero
    (the validity bias is ``-inf``, so softmax weights vanish in IEEE
    rather than shrinking to ~e-30), so a stream's tokens are a function
    of its own prompt alone — continuous batching cannot perturb them.

    All programs set ``_donate_state`` so the executor donates the cache
    buffers and XLA aliases them window-over-window (PR 6 machinery)."""

    # decode-step feed names (the engine builds these arrays per tick)
    DC_TOKENS, DC_POSENC, DC_BIAS, DC_POS, DC_ACTIVE = (
        "dc_tokens", "dc_posenc", "dc_bias", "dc_pos", "dc_active")
    # paged-mode decode feeds (ISSUE 19): the slot->page indirection and
    # this tick's per-slot write destination (trash page when inactive
    # or stalled)
    DC_PTABLE, DC_WPAGE, DC_WOFF = "dc_ptable", "dc_wpage", "dc_woff"
    # prefill feed names (per admitted request)
    PF_TOKENS, PF_SLOT = "pf_tokens", "pf_slot"
    # paged-mode prefill feed: one page id per prompt page of the bucket
    # (trash for bucket pad pages)
    PF_PAGES = "pf_pages"
    # speculative-verify feed names (ISSUE 20): the k+1-position verify
    # step serving/specdec dispatches once per spec tick.  Per-position
    # feeds are indexed — ``SP_TOK.format(j)`` for j in 0..k — because
    # the program is built as k+1 shape-clones of the step body.
    SP_TOK, SP_PE, SP_BIAS_J = "sp_tok{}", "sp_pe{}", "sp_bias{}"
    # per-position K/V write destinations [S]: dense = (slot |
    # max_slots-OOB trash, absolute position), paged = (page | trash
    # page, in-page offset)
    SP_WROW, SP_WOFF = "sp_wrow{}", "sp_woff{}"
    SP_DRAFT, SP_ACTIVE, SP_PTABLE = "sp_draft", "sp_active", "sp_ptable"

    def __init__(self, cfg=None, max_slots=None, max_len=None,
                 prefill_buckets=None, end_id=1, seed=7, paged=None,
                 page_size=None, num_pages=None):
        from ..fluid import envcontract as _ec

        self.cfg = cfg or decode_lm_config()
        if self.cfg.dropout:
            raise ValueError("decode models must be deterministic: "
                             "build the config with dropout=0")
        self.max_slots = int(max_slots if max_slots is not None
                             else _ec.get("PADDLE_SERVE_SLOTS"))
        self.max_len = int(max_len if max_len is not None
                           else _ec.get("PADDLE_SERVE_MAX_LEN"))
        if prefill_buckets is None:
            raw = _ec.get("PADDLE_SERVE_PREFILL_BUCKETS") or ""
            prefill_buckets = [int(b) for b in str(raw).split(",") if b]
        self.prefill_buckets = sorted(
            {int(b) for b in prefill_buckets if int(b) <= self.max_len})
        if not self.prefill_buckets:
            raise ValueError(
                f"no viable prefill bucket <= max_len ({self.max_len})")
        # paged KV cache (ISSUE 19): same program families, but the
        # per-layer caches become [num_pages + 1, page_size, d_model]
        # page pools (row num_pages = trash) addressed through per-tick
        # page-table feeds.  Feed shapes stay fixed, so the closed
        # executable set survives: still 1 step + one per bucket.
        self.paged = bool(_ec.get("PADDLE_SERVE_PAGED")) if paged is None \
            else bool(paged)
        if self.paged:
            self.page_size = int(page_size if page_size is not None
                                 else _ec.get("PADDLE_SERVE_PAGE_SIZE"))
            if self.page_size < 1 or self.max_len % self.page_size:
                raise ValueError(
                    f"page_size ({self.page_size}) must divide max_len "
                    f"({self.max_len})")
            bad = [b for b in self.prefill_buckets
                   if b % self.page_size]
            if bad:
                raise ValueError(
                    f"page_size ({self.page_size}) must divide every "
                    f"prefill bucket; {bad} are not divisible")
            self.pages_per_slot = self.max_len // self.page_size
            np_req = int(num_pages if num_pages is not None
                         else _ec.get("PADDLE_SERVE_NUM_PAGES"))
            # 0 = auto: dense-equal capacity (every slot can run to
            # max_len); smaller pools oversubscribe and rely on the
            # engine's admission backpressure + growth stalls
            self.num_pages = np_req or self.max_slots * self.pages_per_slot
            if self.num_pages < self.pages_per_slot:
                raise ValueError(
                    f"num_pages ({self.num_pages}) cannot hold even one "
                    f"full slot ({self.pages_per_slot} pages)")
            self.trash_page = self.num_pages
        else:
            self.page_size = self.num_pages = self.trash_page = None
            self.pages_per_slot = None
        self.end_id = int(end_id)
        self.seed = int(seed)
        self.vocab_size = int(self.cfg.tgt_vocab_size)
        self.pos_table = _position_encoding(self.max_len, self.cfg.d_model)
        self.startup = fluid.Program()
        self._prefill = {}
        self._spec = {}
        self.step_program, self.step_fetch, self.logits_fetch = \
            self._build_step()

    # -- graph pieces shared by the step and prefill programs --

    def _cache_var(self, name):
        """The persistable cache param (zero-init, frozen): the dense
        [S, L, D] slot cache, or in paged mode the [P + 1, ps, D] page
        pool whose last row is the trash page.  Names keep the
        ``_cache_`` marker either way — the scrub/rebind machinery and
        ``weight_names`` key on it."""
        from ..fluid.initializer import ConstantInitializer
        from ..fluid.layers import tensor as _tensor

        shape = ([self.num_pages + 1, self.page_size, self.cfg.d_model]
                 if self.paged
                 else [self.max_slots, self.max_len, self.cfg.d_model])
        return _tensor.create_parameter(
            shape=shape, dtype="float32",
            attr=ParamAttr(name=name, trainable=False,
                           initializer=ConstantInitializer(0.0)))

    def _layer(self, x, i, attn):
        """One decoder layer over x [n, t, D]; ``attn(q, k, v)`` supplies
        the cache-backed (step) or windowed-causal (prefill) attention."""
        d, f = self.cfg.d_model, self.cfg.d_inner
        proj = dict(num_flatten_dims=2, bias_attr=False)
        q = layers.fc(x, d, param_attr=ParamAttr(name=f"dlm{i}_q_w"), **proj)
        k = layers.fc(x, d, param_attr=ParamAttr(name=f"dlm{i}_k_w"), **proj)
        v = layers.fc(x, d, param_attr=ParamAttr(name=f"dlm{i}_v_w"), **proj)
        ctx = attn(q, k, v)
        o = layers.fc(ctx, d, param_attr=ParamAttr(name=f"dlm{i}_o_w"),
                      **proj)
        x = layers.layer_norm(
            layers.elementwise_add(x, o), begin_norm_axis=2,
            param_attr=ParamAttr(name=f"dlm{i}_ln1_s"),
            bias_attr=ParamAttr(name=f"dlm{i}_ln1_b"))
        h = layers.fc(x, f, act="relu",
                      param_attr=ParamAttr(name=f"dlm{i}_ffn1_w"), **proj)
        ff = layers.fc(h, d, param_attr=ParamAttr(name=f"dlm{i}_ffn2_w"),
                       **proj)
        return layers.layer_norm(
            layers.elementwise_add(x, ff), begin_norm_axis=2,
            param_attr=ParamAttr(name=f"dlm{i}_ln2_s"),
            bias_attr=ParamAttr(name=f"dlm{i}_ln2_b"))

    def _embed(self, tokens, posenc_var):
        emb = layers.embedding(tokens, size=[self.vocab_size,
                                             self.cfg.d_model],
                               param_attr=ParamAttr(name="dlm_emb"))
        return layers.elementwise_add(
            layers.scale(emb, scale=self.cfg.d_model ** 0.5), posenc_var,
            axis=emb.shape and len(emb.shape) - len(posenc_var.shape))

    # -- the one compiled decode step --

    def _build_step(self):
        s, l = self.max_slots, self.max_len
        d, v = self.cfg.d_model, self.vocab_size
        prog = fluid.Program()
        prog.random_seed = self.startup.random_seed = self.seed
        prog._donate_state = True  # single engine worker owns dispatch
        with fluid.program_guard(prog, self.startup), \
                fluid.unique_name.guard():
            tokens = layers.data(self.DC_TOKENS, shape=[s, 1],
                                 dtype="int64", append_batch_size=False)
            posenc = layers.data(self.DC_POSENC, shape=[s, d],
                                 dtype="float32", append_batch_size=False)
            bias = layers.data(self.DC_BIAS, shape=[s, 1, l],
                               dtype="float32", append_batch_size=False)
            pos = layers.data(self.DC_POS, shape=[s], dtype="int64",
                              append_batch_size=False)
            active = layers.data(self.DC_ACTIVE, shape=[s],
                                 dtype="float32", append_batch_size=False)
            slots = layers.assign(np.arange(s, dtype=np.int64))
            if self.paged:
                # slot->page indirection, fed fresh each tick.  Gathered
                # length pages_per_slot * page_size == max_len, so the
                # SAME [S, 1, L] validity bias masks trash/stale pages
                # with exact -inf — bitwise equality with the dense step
                # rides on that.
                ptable = layers.data(
                    self.DC_PTABLE, shape=[s, self.pages_per_slot],
                    dtype="int64", append_batch_size=False)
                wpage = layers.data(self.DC_WPAGE, shape=[s],
                                    dtype="int64", append_batch_size=False)
                woff = layers.data(self.DC_WOFF, shape=[s],
                                   dtype="int64", append_batch_size=False)

            x = layers.reshape(self._embed(tokens, posenc), [s, 1, d])

            def cache_attn(q, k, v_, i):
                ck = self._cache_var(f"dlm{i}_cache_k")
                cv = self._cache_var(f"dlm{i}_cache_v")
                # write BEFORE reading so position `pos` (this token)
                # participates in its own attention window
                if self.paged:
                    # same scatter op, page-pool addressed: row = page,
                    # offset = position within the page (inactive and
                    # stalled slots aim at the trash page)
                    ck = layers.kv_cache_update(ck, k, wpage, woff)
                    cv = layers.kv_cache_update(cv, v_, wpage, woff)
                    return layers.paged_attention(
                        layers.scale(q, scale=d ** -0.5), ck, cv,
                        ptable, bias, scale=1.0)             # [S, 1, D]
                ck = layers.kv_cache_update(ck, k, slots, pos)
                cv = layers.kv_cache_update(cv, v_, slots, pos)
                scores = layers.matmul(
                    layers.scale(q, scale=d ** -0.5), ck,
                    transpose_y=True)                        # [S, 1, L]
                probs = layers.softmax(
                    layers.elementwise_add(scores, bias))
                return layers.matmul(probs, cv)              # [S, 1, D]

            for i in range(self.cfg.n_layer):
                x = self._layer(x, i,
                                lambda q, k, v_, i=i: cache_attn(q, k, v_, i))
            logits = layers.fc(layers.reshape(x, [s, d]), v,
                               bias_attr=False,
                               param_attr=ParamAttr(name="dlm_out_w"))
            nxt = layers.token_select(logits, mask=active,
                                      end_id=self.end_id)
        return prog, nxt.name, logits.name

    # -- bucketed prefill --

    def bucket_for(self, prompt_len):
        """Smallest prefill bucket holding ``prompt_len`` (None = none)."""
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        return None

    def prefill_program(self, plen):
        """The (lazily built, cached) prefill program for bucket ``plen``:
        one request, prompt padded to ``plen``, K/V prefix scattered into
        the fed slot at position 0.  Weights come from the step
        program's startup — this builder's throwaway startup is never
        run."""
        prog = self._prefill.get(plen)
        if prog is not None:
            return prog
        if plen not in self.prefill_buckets:
            raise ValueError(f"{plen} is not a prefill bucket "
                             f"({self.prefill_buckets})")
        d = self.cfg.d_model
        prog, scratch_startup = fluid.Program(), fluid.Program()
        prog.random_seed = scratch_startup.random_seed = self.seed
        prog._donate_state = True
        with fluid.program_guard(prog, scratch_startup), \
                fluid.unique_name.guard():
            tokens = layers.data(self.PF_TOKENS, shape=[1, plen],
                                 dtype="int64", append_batch_size=False)
            if self.paged:
                # per-page destinations instead of a slot id: the K/V
                # window is cut into bucket//page_size page-sized chunks
                # and scattered to wherever the pool placed them (pad
                # pages beyond the prompt are fed the trash page)
                n_pp = plen // self.page_size
                pages = layers.data(self.PF_PAGES, shape=[n_pp],
                                    dtype="int64", append_batch_size=False)
                zeros = layers.fill_constant([n_pp], "int64", 0)
            else:
                slot = layers.data(self.PF_SLOT, shape=[1], dtype="int64",
                                   append_batch_size=False)
                start = layers.fill_constant([1], "int64", 0)
            posenc = layers.assign(self.pos_table[:plen])     # [p, D]
            x = self._embed(tokens, posenc)                   # [1, p, D]

            def window_attn(q, k, v_, i):
                ck = self._cache_var(f"dlm{i}_cache_k")
                cv = self._cache_var(f"dlm{i}_cache_v")
                if self.paged:
                    kr = layers.reshape(k, [n_pp, self.page_size, d])
                    vr = layers.reshape(v_, [n_pp, self.page_size, d])
                    layers.kv_cache_update(ck, kr, pages, zeros)
                    layers.kv_cache_update(cv, vr, pages, zeros)
                else:
                    layers.kv_cache_update(ck, k, slot, start)
                    layers.kv_cache_update(cv, v_, slot, start)
                # the prompt window attends within itself (causal); the
                # cache is write-only here — decode ticks read it
                scores = layers.matmul(
                    layers.scale(q, scale=d ** -0.5), k,
                    transpose_y=True)                        # [1, p, p]
                scores = layers.elementwise_add(
                    scores, _shared_causal_bias(plen, plen), axis=1)
                return layers.matmul(layers.softmax(scores), v_)

            for i in range(self.cfg.n_layer):
                x = self._layer(x, i,
                                lambda q, k, v_, i=i: window_attn(q, k, v_, i))
        self._prefill[plen] = prog
        return prog

    # -- speculative verify (ISSUE 20) --

    def spec_program(self, k):
        """The (lazily built, cached) verify program for speculation
        depth ``k``: ONE fixed-shape dispatch scoring k + 1 positions
        per slot.  Position j's sub-graph is a SHAPE-CLONE of the step
        program's body — embed [S, 1] tokens, project q/k/v, write this
        position's K/V, attend under a [S, 1, L] validity bias, project
        [S, V] logits — repeated k + 1 times over a shared cache (writes
        land in program order, so position j attends over positions
        <= pos + j exactly as sequential decode would).  The k + 1
        logits rows stack into [S, k+1, V] and ``spec_accept`` takes the
        longest draft == argmax prefix plus the correction token.

        Why clones instead of one wide [S, k+1, ·] step: XLA's fusion
        choices change with the position width (the matmul+bias+softmax
        epilogue reassociates), so a wide verify's logits drift ~1e-7
        from the step's — enough to flip an argmax at a near-tie.  With
        same-shaped sub-graphs the compiler has the step program's exact
        fusion problem, so verify logits at position j are bitwise the
        step's at that position; greedy acceptance is then bitwise
        sequential BY CONSTRUCTION, not by tie-luck.  The whole point of
        the verify step is fewer host round-trips and one dispatch per
        tick, which survives; the tests/test_specdec.py bitwise oracles
        enforce this contract.

        The only write-path difference from the step: K/V lands through
        ``kv_cache_scatter`` at explicit fed (row, offset) pairs, so
        non-participating slots steer to the dense out-of-bounds trash
        slot / the paged trash page instead of writing garbage at a
        clamped position.

        Returns ``(prog, tokens_fetch, naccept_fetch, logits_fetch)``;
        the logits fetch is position 0's [S, V] — exactly the plain
        step's logits, so the engine's tick monitor keeps watching the
        same slice."""
        if k < 1:
            raise ValueError(f"speculation depth must be >= 1, got {k}")
        cached = self._spec.get(k)
        if cached is not None:
            return cached
        s, l, w = self.max_slots, self.max_len, k + 1
        d, v = self.cfg.d_model, self.vocab_size
        prog, scratch_startup = fluid.Program(), fluid.Program()
        prog.random_seed = scratch_startup.random_seed = self.seed
        prog._donate_state = True
        with fluid.program_guard(prog, scratch_startup), \
                fluid.unique_name.guard():
            draft = layers.data(self.SP_DRAFT, shape=[s, k],
                                dtype="int64", append_batch_size=False)
            active = layers.data(self.SP_ACTIVE, shape=[s],
                                 dtype="float32", append_batch_size=False)
            if self.paged:
                ptable = layers.data(
                    self.SP_PTABLE, shape=[s, self.pages_per_slot],
                    dtype="int64", append_batch_size=False)
            logit_rows = []
            for j in range(w):
                tokens = layers.data(self.SP_TOK.format(j), shape=[s, 1],
                                     dtype="int64",
                                     append_batch_size=False)
                posenc = layers.data(self.SP_PE.format(j), shape=[s, d],
                                     dtype="float32",
                                     append_batch_size=False)
                bias = layers.data(self.SP_BIAS_J.format(j),
                                   shape=[s, 1, l], dtype="float32",
                                   append_batch_size=False)
                wrow = layers.data(self.SP_WROW.format(j), shape=[s],
                                   dtype="int64", append_batch_size=False)
                woff = layers.data(self.SP_WOFF.format(j), shape=[s],
                                   dtype="int64", append_batch_size=False)

                x = layers.reshape(self._embed(tokens, posenc), [s, 1, d])

                def sub_attn(q, kk, v_, i, bias=bias, wrow=wrow,
                             woff=woff):
                    ck = self._cache_var(f"dlm{i}_cache_k")
                    cv = self._cache_var(f"dlm{i}_cache_v")
                    ck = layers.kv_cache_scatter(
                        ck, layers.reshape(kk, [s, d]), wrow, woff)
                    cv = layers.kv_cache_scatter(
                        cv, layers.reshape(v_, [s, d]), wrow, woff)
                    if self.paged:
                        return layers.paged_attention(
                            layers.scale(q, scale=d ** -0.5), ck, cv,
                            ptable, bias, scale=1.0)         # [S, 1, D]
                    scores = layers.matmul(
                        layers.scale(q, scale=d ** -0.5), ck,
                        transpose_y=True)                    # [S, 1, L]
                    probs = layers.softmax(
                        layers.elementwise_add(scores, bias))
                    return layers.matmul(probs, cv)          # [S, 1, D]

                for i in range(self.cfg.n_layer):
                    x = self._layer(
                        x, i,
                        lambda q, kk, v_, i=i: sub_attn(q, kk, v_, i))
                logit_rows.append(layers.fc(
                    layers.reshape(x, [s, d]), v, bias_attr=False,
                    param_attr=ParamAttr(name="dlm_out_w")))
            logits = layers.concat(
                [layers.reshape(r, [s, 1, v]) for r in logit_rows],
                axis=1)                                      # [S, w, V]
            toks, nacc = layers.spec_accept(logits, draft, mask=active,
                                            end_id=self.end_id)
        out = (prog, toks.name, nacc.name, logit_rows[0].name)
        self._spec[k] = out
        return out

    def weight_names(self):
        """The hot-swap rebind set: every learned weight shared by name
        across the startup/prefill/step family.  Excludes the
        ``dlm{i}_cache_k/v`` slot caches — those are engine-lifetime
        activations of whichever weights wrote them, never checkpoint
        state (a swap that rebound them would tear every in-flight
        stream's K/V prefix)."""
        return sorted(v.name for v in self.startup.list_vars()
                      if v.persistable and "_cache_" not in v.name)

    # -- host-side helpers the engine uses to build tick feeds --

    def posenc_rows(self, positions):
        """pos_table rows for an int position vector (clipped in-range)."""
        idx = np.clip(np.asarray(positions, np.int64), 0, self.max_len - 1)
        return self.pos_table[idx]

    def validity_bias(self, positions):
        """[S, 1, L] additive bias: 0 where cache index <= pos, -inf
        elsewhere.  EXACT -inf on purpose — stale cache rows beyond a
        stream's frontier must contribute exactly zero attention weight
        (IEEE exp(-inf)=0), which is what makes slot reuse invisible to
        the generated bits."""
        pos = np.asarray(positions, np.int64).reshape(-1, 1)
        idx = np.arange(self.max_len, dtype=np.int64)[None, :]
        bias = np.where(idx <= pos, 0.0, -np.inf).astype(np.float32)
        return bias.reshape(len(positions), 1, self.max_len)


def build(cfg=None, src_len=64, tgt_len=64, lr=1e-3, warmup_steps=None):
    """Full training graph with Adam (+ optional noam decay).  Returns
    (src_word, tgt_word, lbl_word, avg_cost)."""
    cfg = cfg or tiny_config()
    src_word, tgt_word, lbl_word, avg_cost, _ = forward(cfg, src_len, tgt_len)
    if warmup_steps:
        lr_sched = layers.learning_rate_scheduler.noam_decay(
            cfg.d_model, warmup_steps)
        opt = fluid.optimizer.Adam(learning_rate=lr_sched,
                                   beta1=0.9, beta2=0.98, epsilon=1e-9)
    else:
        opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.98,
                                   epsilon=1e-9)
    opt.minimize(avg_cost)
    return src_word, tgt_word, lbl_word, avg_cost
