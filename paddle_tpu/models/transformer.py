"""Transformer encoder-decoder (the second driver metric: Transformer-base
tokens/sec/chip).

Functional contract follows the reference's Transformer test model
(python/paddle/fluid/tests/unittests/transformer_model.py: multi-head
attention, position encoding, pre/post-process residual+norm+dropout,
label-smoothed softmax CE) but the design is TPU-first rather than a
translation: everything is static-shape dense [batch, seq_len] tensors, the
causal and padding masks are additive biases broadcast into the pre-softmax
logits (no LoD, no data-dependent shapes), and the whole step traces into a
single XLA program whose attention/FFN matmuls tile onto the MXU.
"""

from __future__ import annotations

import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.initializer import NumpyArrayInitializer
from ..fluid.param_attr import ParamAttr

_NEG_INF = -1e9


class Config:
    def __init__(self, name, src_vocab_size, tgt_vocab_size, d_model,
                 d_inner, n_head, n_layer, dropout=0.1, label_smooth=0.1,
                 moe_experts=0, moe_top_k=2, moe_aux_weight=1e-2,
                 stacked=False, ring_attention=False, n_microbatches=4,
                 recompute=False, flash_attention=None):
        self.name = name
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout
        self.label_smooth = label_smooth
        # moe_experts > 0 replaces every FFN with an expert-parallel MoE
        # layer (Switch-style; experts shard over an "ep" mesh axis)
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_aux_weight = moe_aux_weight
        # stacked=True builds the encoder/decoder as ONE mesh-aware
        # layer-stack op with [L, ...] params (layers.transformer_*_stack):
        # pipeline-parallel over "pp", Megatron-TP over "mp", ring-
        # attention over "sp" — the pipeline-capable flagship build.
        # Residual dropout only in this mode (see transformer_stack).
        self.stacked = stacked
        # ring_attention=True keeps the per-layer graph but routes every
        # attention through layers.ring_attention, so the UNstacked model
        # sequence-parallelizes over an "sp" mesh axis too.  Attention-
        # probability dropout is skipped in this mode (the [T, T] matrix
        # never materializes under the ring).
        self.ring_attention = ring_attention
        # flash_attention: True routes every attention through the Pallas
        # streamed kernel (fwd + bwd, ops/pallas_flash.py), False forbids
        # it, None = auto (on for TPU backends; PADDLE_TPU_FLASH
        # overrides).  Attention-probability dropout is skipped on the
        # flash path (the [T, T] matrix never materializes), like ring.
        self.flash_attention = flash_attention
        self.n_microbatches = n_microbatches
        # recompute=True (stacked mode) wraps each layer in
        # jax.checkpoint: backward rematerializes activations layer by
        # layer — peak memory O(T*D) instead of O(L*T*D) for long
        # sequences at the cost of one extra forward
        self.recompute = recompute


def base_config():
    """Transformer-base (Vaswani et al.): d_model 512, 8 heads, 6 layers."""
    return Config("base", src_vocab_size=30000, tgt_vocab_size=30000,
                  d_model=512, d_inner=2048, n_head=8, n_layer=6)


def tiny_config():
    """CPU-test scale."""
    return Config("tiny", src_vocab_size=1000, tgt_vocab_size=1000,
                  d_model=64, d_inner=128, n_head=4, n_layer=2)


def _position_encoding(max_len, d_model):
    """Sinusoid table [max_len, d_model] (Vaswani et al. eq. 5)."""
    pos = np.arange(max_len, dtype=np.float64)[:, None]
    div = np.exp(np.arange(0, d_model, 2, dtype=np.float64)
                 * -(np.log(10000.0) / d_model))
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    return table


def _shared_causal_bias(lq, lk):
    """One additive triu causal mask per (program, shape) — every decoder
    layer shares the same constant var instead of re-materializing it."""
    from .. import fluid as _fluid

    prog = _fluid.default_main_program()
    cache = getattr(prog, "_causal_bias_cache", None)
    if cache is None:
        cache = prog._causal_bias_cache = {}
    var = cache.get((lq, lk))
    if var is None:
        causal_np = np.triu(np.full((lq, lk), _NEG_INF, np.float32), k=1)
        var = cache[(lq, lk)] = layers.assign(causal_np)
    return var


def _postprocess(prev, out, dropout):
    """Residual add + layer norm (+ dropout on the sublayer output)."""
    if dropout:
        out = layers.dropout(out, dropout_prob=dropout)
    return layers.layer_norm(layers.elementwise_add(prev, out),
                             begin_norm_axis=2)


def _multi_head_attention(q_in, k_in, v_in, bias, d_model, n_head,
                          dropout, prefix, causal=False, use_ring=False,
                          flash=None):
    """[b, lq, d] x [b, lk, d] -> [b, lq, d]; bias broadcasts into the
    [b, h, lq, lk] logits (None, [lq, lk] causal, or [b, 1, 1, lk] padding).

    use_ring=True routes the attention through layers.ring_attention
    (sequence-parallel over an "sp" mesh axis, mathematically identical
    single-device); the causal mask is then expressed via the op's
    ``causal`` flag and ``bias`` must be a key-position padding bias
    ([b, 1, 1, lk]) or None — and attention-probability dropout is skipped
    (the ring never materializes the probability matrix)."""
    lq, lk = q_in.shape[1], k_in.shape[1]
    d_k = d_model // n_head
    q = layers.fc(q_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_q_w"))
    k = layers.fc(k_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_k_w"))
    v = layers.fc(v_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=ParamAttr(name=f"{prefix}_v_w"))
    # [b, l, d] -> [b, h, l, d_k]
    q = layers.transpose(layers.reshape(q, [-1, lq, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    k = layers.transpose(layers.reshape(k, [-1, lk, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    v = layers.transpose(layers.reshape(v, [-1, lk, n_head, d_k]),
                         perm=[0, 2, 1, 3])
    from ..ops.attention_ops import _flash_decision
    if use_ring or flash or (flash is None and _flash_decision()):
        # the fused attention op: executor picks ring (sp mesh axis) /
        # Pallas flash / XLA full softmax; prob-dropout is skipped.
        # flash=None auto-routes here when the backend would take the
        # Pallas path (TPU, PADDLE_TPU_FLASH honored) so the Config
        # docstring's "None = auto" holds for dense builds too
        ctx = layers.ring_attention(q, k, v, causal=causal,
                                    scale=d_k ** -0.5, bias=bias,
                                    flash=flash)
    else:
        logits = layers.matmul(layers.scale(q, scale=d_k ** -0.5), k,
                               transpose_y=True)
        if causal:
            # one shared [lq, lk] mask var per program+shape: layers would
            # otherwise each carry their own identical triu constant
            logits = layers.elementwise_add(logits,
                                            _shared_causal_bias(lq, lk))
        if bias is not None:
            logits = layers.elementwise_add(logits, bias)
        weights = layers.softmax(logits)
        if dropout:
            weights = layers.dropout(weights, dropout_prob=dropout)
        ctx = layers.matmul(weights, v)                  # [b, h, lq, d_k]
    ctx = layers.reshape(layers.transpose(ctx, perm=[0, 2, 1, 3]),
                         [-1, lq, d_model])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=ParamAttr(name=f"{prefix}_o_w"))


def _ffn(x, d_inner, d_model, prefix, cfg=None, aux_losses=None):
    if cfg is not None and cfg.moe_experts:
        out, aux = layers.moe_ffn(x, num_experts=cfg.moe_experts,
                                  hidden_size=d_inner,
                                  top_k=cfg.moe_top_k)
        if aux_losses is not None:
            aux_losses.append(aux)
        return out
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu",
                  param_attr=ParamAttr(name=f"{prefix}_ffn1_w"))
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(name=f"{prefix}_ffn2_w"))


def _embed(word, vocab_size, seq_len, cfg, name):
    emb = layers.embedding(
        word, size=[vocab_size, cfg.d_model],
        param_attr=ParamAttr(
            name=f"{name}_emb",
            initializer=fluid.initializer.NormalInitializer(
                0.0, cfg.d_model ** -0.5)))
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    pos = layers.create_parameter(
        shape=[seq_len, cfg.d_model], dtype="float32",
        attr=ParamAttr(name=f"{name}_pos_enc",
                       initializer=NumpyArrayInitializer(
                           _position_encoding(seq_len, cfg.d_model)),
                       trainable=False))
    out = layers.elementwise_add(emb, pos)
    if cfg.dropout:
        out = layers.dropout(out, dropout_prob=cfg.dropout)
    return out


def _padding_bias(word, seq_len):
    """[b, len] int ids -> additive bias [b, 1, 1, len]: NEG_INF at pad(0)."""
    zeros = layers.fill_constant_batch_size_like(
        word, shape=[-1, seq_len], dtype="int64", value=0)
    is_pad = layers.cast(layers.equal(word, zeros), "float32")
    bias = layers.scale(is_pad, scale=_NEG_INF)
    return layers.reshape(bias, [-1, 1, 1, seq_len])


def moe_config():
    """Switch-Transformer-style MoE variant of the tiny config (expert
    parallelism demo/test model; SURVEY.md §2.6: MoE/EP beyond-reference)."""
    c = tiny_config()
    c.name = "moe_tiny"
    c.moe_experts = 4
    return c


def encoder(src_word, cfg, src_len, aux_losses=None):
    enc = _embed(src_word, cfg.src_vocab_size, src_len, cfg, "src")
    src_bias = _padding_bias(src_word, src_len)
    if cfg.stacked:
        enc = layers.transformer_encoder_stack(
            enc, bias=src_bias, n_layer=cfg.n_layer, n_head=cfg.n_head,
            d_inner=cfg.d_inner, dropout=cfg.dropout,
            n_microbatches=cfg.n_microbatches,
            recompute=getattr(cfg, "recompute", False),
            flash=getattr(cfg, "flash_attention", None))
        return enc, src_bias
    for i in range(cfg.n_layer):
        attn = _multi_head_attention(
            enc, enc, enc, src_bias, cfg.d_model, cfg.n_head, cfg.dropout,
            prefix=f"enc{i}_self", use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        enc = _postprocess(enc, attn, cfg.dropout)
        ff = _ffn(enc, cfg.d_inner, cfg.d_model, prefix=f"enc{i}",
                  cfg=cfg, aux_losses=aux_losses)
        enc = _postprocess(enc, ff, cfg.dropout)
    return enc, src_bias


def decoder(tgt_word, enc_out, src_bias, cfg, tgt_len, aux_losses=None):
    dec = _embed(tgt_word, cfg.tgt_vocab_size, tgt_len, cfg, "tgt")
    if cfg.stacked:
        dec = layers.transformer_decoder_stack(
            dec, enc_out, src_bias=src_bias, n_layer=cfg.n_layer,
            n_head=cfg.n_head, d_inner=cfg.d_inner, dropout=cfg.dropout,
            n_microbatches=cfg.n_microbatches,
            recompute=getattr(cfg, "recompute", False),
            flash=getattr(cfg, "flash_attention", None))
        return layers.fc(dec, cfg.tgt_vocab_size, num_flatten_dims=2,
                         param_attr=ParamAttr(name="out_proj_w"))
    for i in range(cfg.n_layer):
        self_attn = _multi_head_attention(
            dec, dec, dec, None, cfg.d_model, cfg.n_head, cfg.dropout,
            prefix=f"dec{i}_self", causal=True, use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        dec = _postprocess(dec, self_attn, cfg.dropout)
        cross = _multi_head_attention(
            dec, enc_out, enc_out, src_bias, cfg.d_model, cfg.n_head,
            cfg.dropout, prefix=f"dec{i}_cross", use_ring=cfg.ring_attention,
            flash=getattr(cfg, "flash_attention", None))
        dec = _postprocess(dec, cross, cfg.dropout)
        ff = _ffn(dec, cfg.d_inner, cfg.d_model, prefix=f"dec{i}",
                  cfg=cfg, aux_losses=aux_losses)
        dec = _postprocess(dec, ff, cfg.dropout)
    return layers.fc(dec, cfg.tgt_vocab_size, num_flatten_dims=2,
                     param_attr=ParamAttr(name="out_proj_w"))


def forward(cfg, src_len, tgt_len):
    """Build data layers + logits + label-smoothed CE loss.  Returns
    (src_word, tgt_word, lbl_word, avg_cost, logits)."""
    src_word = layers.data(name="src_word", shape=[src_len], dtype="int64")
    tgt_word = layers.data(name="tgt_word", shape=[tgt_len], dtype="int64")
    lbl_word = layers.data(name="lbl_word", shape=[tgt_len, 1], dtype="int64")

    aux_losses = []
    enc_out, src_bias = encoder(src_word, cfg, src_len, aux_losses)
    logits = decoder(tgt_word, enc_out, src_bias, cfg, tgt_len, aux_losses)

    if cfg.label_smooth:
        hot = layers.one_hot(lbl_word, cfg.tgt_vocab_size)
        smooth = layers.label_smooth(hot, epsilon=cfg.label_smooth)
        cost = layers.softmax_with_cross_entropy(logits, smooth,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(logits, lbl_word)
    # mask loss at pad targets so padding doesn't dilute the objective
    zeros = layers.fill_constant_batch_size_like(
        lbl_word, shape=[-1, tgt_len, 1], dtype="int64", value=0)
    non_pad = layers.cast(
        layers.logical_not(layers.equal(lbl_word, zeros)), "float32")
    cost = layers.elementwise_mul(cost, non_pad)
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(cost),
        layers.elementwise_add(layers.reduce_sum(non_pad),
                               layers.fill_constant([1], "float32", 1e-8)))
    for aux in aux_losses:  # Switch load-balancing losses (MoE configs)
        avg_cost = layers.elementwise_add(
            avg_cost, layers.scale(aux, scale=cfg.moe_aux_weight))
    return src_word, tgt_word, lbl_word, avg_cost, logits


def build(cfg=None, src_len=64, tgt_len=64, lr=1e-3, warmup_steps=None):
    """Full training graph with Adam (+ optional noam decay).  Returns
    (src_word, tgt_word, lbl_word, avg_cost)."""
    cfg = cfg or tiny_config()
    src_word, tgt_word, lbl_word, avg_cost, _ = forward(cfg, src_len, tgt_len)
    if warmup_steps:
        lr_sched = layers.learning_rate_scheduler.noam_decay(
            cfg.d_model, warmup_steps)
        opt = fluid.optimizer.Adam(learning_rate=lr_sched,
                                   beta1=0.9, beta2=0.98, epsilon=1e-9)
    else:
        opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.98,
                                   epsilon=1e-9)
    opt.minimize(avg_cost)
    return src_word, tgt_word, lbl_word, avg_cost
