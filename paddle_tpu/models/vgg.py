"""VGG-16 (ref: benchmark/fluid/vgg.py)."""

from __future__ import annotations

from .. import fluid


def vgg16_bn_drop(input, class_dim=1000):
    def conv_block(inp, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=inp, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 64, 2, [0.3, 0.0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0.0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0.0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0.0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0.0])

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act="relu")
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    prediction = fluid.layers.fc(input=fc2, size=class_dim, act="softmax")
    return prediction


def build(class_dim=10, image_shape=(3, 32, 32), lr=0.01):
    img = fluid.layers.data(name="img", shape=list(image_shape),
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prediction = vgg16_bn_drop(img, class_dim)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    opt = fluid.optimizer.Adam(learning_rate=lr)
    opt.minimize(loss)
    return img, label, prediction, loss, acc
