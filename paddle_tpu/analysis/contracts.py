"""Pass 3: donation & AMP runtime-contract analysis.

The execution layer enforces these contracts at runtime (PE rejects
per-step fp16-scale programs, run_steps rejects eager ops, donation is
training-only) — this pass turns each reject into a pre-compile
diagnostic with a named code and a fix hint, and statically flags the
donation hazards the runtime can only paper over.
"""

from __future__ import annotations

from typing import Sequence, Set

from ..fluid.framework import OpRole, Parameter, Program


def _has_eager(program: Program, block_idx: int = 0) -> bool:
    from ..ops.array_ops import EAGER_OPS

    def op_eager(op):
        base = op.type[:-5] if op.type.endswith("_grad") else op.type
        if base in EAGER_OPS:
            return True
        sub = op.attr("sub_block")
        if isinstance(sub, int):
            return any(op_eager(b) for b in program.block(sub).ops)
        return False

    return any(op_eager(op) for op in program.block(block_idx).ops)


def mutated_persistables(program: Program) -> Set[str]:
    gb = program.global_block()
    out: Set[str] = set()
    for op in gb.ops:
        for n in op.output_arg_names:
            if n and gb._has_var_recursive(n) \
                    and gb._var_recursive(n).persistable:
                out.add(n)
    return out


def run_contract_pass(program: Program, fetch_names: Sequence[str],
                      kind: str, diags: list) -> None:
    from . import Diagnostic
    from ..fluid import envcontract

    scale_vars = getattr(program, "_loss_scale_vars", None)

    # fp16 dynamic loss scale on the per-step ParallelExecutor path: the
    # backward seed goes unscaled while append_unscale_ops still divides
    # grads — silently wrong math, rejected at runtime today
    if scale_vars is not None and kind == "pe_run":
        diags.append(Diagnostic(
            "AN401", "error",
            "dynamic fp16 loss-scale program headed for the per-step "
            "ParallelExecutor path (unscaled backward seed + unscale ops "
            "= silently wrong gradients)",
            hint="use ParallelExecutor.run_steps (the windowed sharded "
                 "path folds the scale update into the scan carry), or "
                 "train in bfloat16 which needs no scaling"))

    # fused windows cannot scan data-dependent eager islands
    if kind in ("run_steps", "pe_run_steps") and _has_eager(program):
        diags.append(Diagnostic(
            "AN402", "error",
            "program contains data-dependent eager ops; a fused "
            "run_steps window cannot scan them",
            hint="use Executor.run per step (eager-island segmentation), "
                 "or move the data-dependent tail out of the training "
                 "program"))

    # an inference program (clone(for_test=True) — predictor clones may
    # share its scope concurrently) that still carries optimizer-role ops
    # mutates shared Parameters under its readers.  Keyed on _is_test,
    # NOT on a missing param/grad list: hand-built training programs
    # (append_backward + manual sgd appends, the reference-book style)
    # legitimately never record one.
    if getattr(program, "_is_test", False):
        gb = program.global_block()
        for idx, op in enumerate(gb.ops):
            role = int(op.attr(OpRole.KEY, OpRole.Forward))
            if role != OpRole.Optimize:
                continue
            wrote = [n for n in op.output_arg_names
                     if n and gb._has_var_recursive(n)
                     and isinstance(gb._var_recursive(n), Parameter)]
            if wrote:
                diags.append(Diagnostic(
                    "AN301", "error",
                    f"op #{idx} ({op.type}) is an optimizer-role op "
                    f"writing shared parameter(s) {wrote} in a program "
                    f"with no recorded param/grad list — predictor "
                    f"clones sharing this scope would race on (and, if "
                    f"donated, free) live state",
                    op_idx=idx, op_type=op.type,
                    hint="build inference programs with "
                         "clone(for_test=True) (drops optimizer ops), or "
                         "keep _params_grads on the training program"))
                break

    # donated-buffer read-after-commit: a fetch that aliases mutated
    # persistable state on a donating program.  Executor.run copies the
    # returned handle, but any scope handle taken BEFORE the dispatch is
    # dead after it — worth a note at verify time.
    if program._params_grads is not None \
            and envcontract.get("PADDLE_TPU_DONATE") \
            and kind in ("run", "run_steps", "pe_run_steps"):
        mutated = mutated_persistables(program)
        aliased = sorted(set(fetch_names) & mutated)
        if aliased:
            diags.append(Diagnostic(
                "AN302", "info",
                f"fetch(es) {aliased} alias donated training state: the "
                f"dispatch invalidates the input buffer and the executor "
                f"returns a device copy",
                hint="don't hold pre-dispatch scope handles to these "
                     "vars across the run; PADDLE_TPU_DONATE=0 disables "
                     "donation for debugging"))
