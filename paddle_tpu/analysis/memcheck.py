"""Pass 5: pre-flight peak-HBM estimation (the AN5xx family).

Answers "will this program fit, and what is it spending HBM on?" BEFORE
any trace or compile — the memory twin of the AN204 collective estimate,
built on the same shape/dtype facts the infer pass already derived:

 - **persistent bytes**: parameters, optimizer accumulators and every
   other persistable var, each divided by its spec-table shard extent
   (the Megatron column/row parity from ``spmd_check._chain``: embedding
   and even-order linear weights split over ``fsdp``×``tp``, odd orders
   over ``tp``×``fsdp``; accumulators follow their owning param);
 - **transient high-water**: a liveness walk over the block — every
   non-persistable var (activations, gradients, feeds) goes live at its
   producing op (feeds at op 0) and dies after its last consumer; the
   high-water mark is the max live sum over op positions, with
   batch-leading tensors divided by the mesh's ``dp`` extent.  Gradients
   need no separate term: ``append_backward`` materializes them as
   ordinary block vars, so the walk prices them where they actually live;
 - **donation**: a donating training program updates state in place
   (input and output buffers alias); with donation off every mutated
   persistable needs a second buffer, which is added back.

The estimate lands as one AN501 info note (and on the
``analysis.mem_peak_est`` gauge, next to the post-compile
``memory.peak_bytes`` truth it is cross-checked against in tests).  With
``PADDLE_MEM_BUDGET_MB`` set, an over-budget estimate is AN502 — an
*error*, so ``PADDLE_TPU_VERIFY=strict`` refuses the program before
compile — and a >90% estimate is the AN503 headroom warning.  Per-op
attribution: the top live tensors at the high-water point are named in
the diagnostics and returned in the estimate dict (``top``), so the
answer to "what is it spending HBM on" is op-granular, not one number.

Unknown shapes degrade silently: vars the infer pass could not type
contribute nothing (never a false positive), and a program with no
sizable facts yields no estimate at all.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..fluid.framework import Parameter, Program

_SKIP_OPS = frozenset(["feed", "fetch", "read", "create_py_reader"])


def _dtype_bytes(dtype) -> Optional[int]:
    try:
        return int(np.dtype(dtype).itemsize)
    except (TypeError, ValueError):
        return None


def _nbytes(info) -> Optional[int]:
    """VarInfo (shape, dtype) -> bytes, None when unknown."""
    if info is None:
        return None
    shape, dtype = info
    item = _dtype_bytes(dtype)
    if item is None:
        return None
    n = 1
    for d in shape:
        if d is None or int(d) < 0:
            return None
        n *= int(d)
    return n * item


def _declared_info(block, name, batch_hint: int):
    if not block._has_var_recursive(name):
        return None
    v = block._var_recursive(name)
    if v.shape is None or v.dtype is None:
        return None
    try:
        return (tuple(batch_hint if d in (-1, None) else int(d)
                      for d in v.shape), str(np.dtype(v.dtype)))
    except TypeError:
        return None


def _param_divisors(program: Program, axes: Dict[str, int]
                    ) -> Dict[str, int]:
    """Per-var shard divisor under the canonical spec table: chain-parity
    column/row splits for 2-D linear/embedding weights (checked for
    divisibility, like ``spmd.infer_param_specs`` degradation), with
    accumulators inheriting their owner's divisor."""
    from .spmd_check import _chain

    tp = axes.get("tp", axes.get("mp", 1))
    fsdp = axes.get("fsdp", 1)
    gb = program.global_block()
    div: Dict[str, int] = {}
    if tp <= 1 and fsdp <= 1:
        return div
    order_of: Dict[str, Optional[int]] = {}
    for _idx, _op_type, name, order in _chain(program):
        if name not in order_of:
            order_of[name] = order
    shapes: Dict[str, tuple] = {}
    for name, order in order_of.items():
        v = gb.vars.get(name)
        if v is None or not isinstance(v, Parameter) or v.shape is None \
                or len(v.shape) != 2:
            continue
        shape = tuple(int(d) for d in v.shape)
        shapes[name] = shape
        # embedding/even order: P(fsdp, tp); odd order: P(tp, fsdp)
        if order is None or order % 2 == 0:
            spec = (fsdp, tp)
        else:
            spec = (tp, fsdp)
        d = 1
        for dim, ext in zip(shape, spec):
            if ext > 1 and dim % ext == 0:
                d *= ext
        if d > 1:
            div[name] = d
    # accumulators follow their param (same-shape; the optimizer registry
    # first, the name-containment fallback for deserialized programs)
    acc_owner = getattr(program, "_accumulator_owner", {}) or {}
    for name, v in gb.vars.items():
        if name in div or not getattr(v, "persistable", False) \
                or v.shape is None:
            continue
        shape = tuple(int(d) if d is not None else -1 for d in v.shape)
        owner = acc_owner.get(name)
        if owner is None:
            owner = next((p for p in shapes if p in name), None)
        if owner in div and shapes.get(owner) == shape:
            div[name] = div[owner]
    return div


def estimate_program_memory(program: Program, env: Dict[str, object],
                            axes: Dict[str, int],
                            feed_infos: Dict[str, object],
                            fetch_names, batch_hint: int = 8,
                            block_idx: int = 0) -> Optional[dict]:
    """The pre-flight peak-HBM estimate (per device, bytes).  ``env`` is
    the infer pass's name -> (shape, dtype) environment; ``axes`` the
    mesh's {axis: extent} map (empty = single device).  Returns None when
    nothing sizable is known."""
    from ..fluid import envcontract

    block = program.block(block_idx)
    gb = program.global_block()
    dp = axes.get("dp", 1)
    pdiv = _param_divisors(program, axes)

    def info_of(name):
        info = env.get(name)
        if info is None:
            info = _declared_info(block, name, batch_hint)
        return info

    def is_persistable(name) -> bool:
        return block._has_var_recursive(name) \
            and block._var_recursive(name).persistable

    # -- persistent residency: every persistable var, shard-divided --
    persistent = 0
    persistent_known = 0
    per_param: Dict[str, int] = {}
    for name, v in gb.vars.items():
        if not getattr(v, "persistable", False):
            continue
        b = _nbytes(info_of(name))
        if b is None:
            continue
        b //= max(1, pdiv.get(name, 1))
        persistent += b
        persistent_known += 1
        per_param[name] = b

    # -- transient high-water: liveness walk over the kept ops --
    ops = [op for op in block.ops if op.type not in _SKIP_OPS]
    first_write: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    produced_by: Dict[str, tuple] = {}
    for name in feed_infos:
        first_write.setdefault(name, 0)
        last_use.setdefault(name, 0)
    for idx, op in enumerate(ops):
        for name in op.input_arg_names:
            if name:
                last_use[name] = idx
        for name in op.output_arg_names:
            if name:
                first_write.setdefault(name, idx)
                last_use[name] = max(last_use.get(name, idx), idx)
                produced_by.setdefault(name, (idx, op.type))
    for name in fetch_names:
        if name in first_write:
            last_use[name] = len(ops) - 1

    def transient_bytes(name) -> Optional[int]:
        b = _nbytes(info_of(name))
        if b is None:
            return None
        info = info_of(name)
        if dp > 1 and info and info[0] and len(info[0]) >= 1 \
                and int(info[0][0]) % dp == 0 and int(info[0][0]) >= dp:
            # batch-leading tensors shard over the data axis
            b //= dp
        return b

    delta = [0] * (len(ops) + 2)
    sized: List[tuple] = []  # (name, bytes, birth, death)
    for name, birth in first_write.items():
        if is_persistable(name):
            continue
        b = transient_bytes(name)
        if not b:
            continue
        death = last_use.get(name, birth)
        delta[birth] += b
        delta[death + 1] -= b
        sized.append((name, b, birth, death))
    high_water = 0
    hw_idx = 0
    running = 0
    for i in range(len(ops) + 1):
        running += delta[i]
        if running > high_water:
            high_water, hw_idx = running, i

    # -- donation: non-donating programs double-buffer mutated state --
    donate = program._params_grads is not None \
        and bool(envcontract.get("PADDLE_TPU_DONATE"))
    donation_extra = 0
    if program._params_grads is not None and not donate:
        mutated = {n for op in ops for n in op.output_arg_names
                   if n and is_persistable(n)}
        donation_extra = sum(
            b for n, b in per_param.items() if n in mutated)

    if persistent_known == 0 and not sized:
        return None

    # -- per-op attribution at the high-water point --
    top = []
    for name, b, birth, death in sized:
        if birth <= hw_idx <= death:
            op_idx, op_type = produced_by.get(name, (None, "feed"))
            top.append({"var": name, "bytes": int(b), "op_idx": op_idx,
                        "op_type": op_type})
    top.sort(key=lambda r: -r["bytes"])
    top = top[:5]

    peak = persistent + donation_extra + high_water
    return {
        "peak_bytes": int(peak),
        "persistent_bytes": int(persistent),
        "transient_high_water_bytes": int(high_water),
        "donation_extra_bytes": int(donation_extra),
        "donated": bool(donate),
        "high_water_op_idx": int(hw_idx),
        "mesh_axes": dict(axes),
        "top": top,
    }


def run_memcheck_pass(program: Program, block_idx: int,
                      env: Dict[str, object], axes: Dict[str, int],
                      feed_infos: Dict[str, object], fetch_names,
                      diags: list, batch_hint: int = 8) -> Optional[dict]:
    """Append the AN5xx diagnostics; returns the estimate dict (None when
    nothing is statically sizable)."""
    from . import Diagnostic
    from ..fluid import envcontract

    est = estimate_program_memory(program, env or {}, axes, feed_infos,
                                  fetch_names, batch_hint=batch_hint,
                                  block_idx=block_idx)
    if est is None:
        return None
    mb = est["peak_bytes"] / (1 << 20)
    label = "x".join(f"{a}{n}" for a, n in axes.items()) or "single-device"
    attrib = ", ".join(
        f"{r['var']}[{r['bytes']}B"
        + (f" @op#{r['op_idx']}({r['op_type']})"
           if r["op_idx"] is not None else "") + "]"
        for r in est["top"][:3])
    diags.append(Diagnostic(
        "AN501", "info",
        f"pre-flight peak-HBM estimate: {mb:.2f} MB per device on "
        f"{label} (persistent {est['persistent_bytes']} B + transient "
        f"high-water {est['transient_high_water_bytes']} B at op "
        f"#{est['high_water_op_idx']}"
        + (f" + non-donated state {est['donation_extra_bytes']} B"
           if est["donation_extra_bytes"] else "")
        + (f"; top live: {attrib}" if attrib else "") + ")",
        hint="compare with the memory.peak_bytes gauge after compile"))
    try:
        from .. import observe

        observe.registry().set_gauge(
            "analysis.mem_peak_est", float(est["peak_bytes"]),
            labels={"mesh": label} if axes else None)
    except Exception:
        pass
    budget_mb = envcontract.get("PADDLE_MEM_BUDGET_MB")
    if budget_mb is not None:
        budget_mb = float(budget_mb)
        if mb > budget_mb:
            diags.append(Diagnostic(
                "AN502", "error",
                f"estimated peak HBM {mb:.2f} MB exceeds "
                f"PADDLE_MEM_BUDGET_MB={budget_mb:g} on {label}"
                + (f"; top live: {attrib}" if attrib else ""),
                hint="shrink the batch/window, shard over more mesh axes, "
                     "or raise the budget — this program would "
                     "RESOURCE_EXHAUSTED after seconds of compile"))
        elif mb > 0.9 * budget_mb:
            diags.append(Diagnostic(
                "AN503", "warn",
                f"estimated peak HBM {mb:.2f} MB is within 10% of "
                f"PADDLE_MEM_BUDGET_MB={budget_mb:g} on {label}",
                hint="fragmentation and padding eat the remaining "
                     "headroom first; treat this as over budget"))
    return est
