"""``python -m paddle_tpu.analysis`` — the program-lint CLI.

    # lint a saved inference model directory (save_inference_model layout)
    python -m paddle_tpu.analysis lint --dir /path/to/model [--strict]

    # lint an in-tree benchmark program builder
    python -m paddle_tpu.analysis lint --model mlp|mnist_cnn|resnet|transformer

    # static SPMD layout check against a mesh no local device has to match
    python -m paddle_tpu.analysis lint --model transformer --mesh dp4,tp2

    # CI round-trip (<2s): build, lint, seed one defect, confirm the code
    python -m paddle_tpu.analysis --smoke

Exit code: 0 clean, 1 = error-severity findings (always with --strict,
otherwise they print as warnings), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_model(name: str):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    framework.fresh_session()
    if name == "mlp":
        from paddle_tpu.models import mnist

        img, label, pred, loss, acc = mnist.mlp()
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
        feed, fetches = ["img", "label"], [loss, acc]
    elif name == "mnist_cnn":
        from paddle_tpu.models import mnist

        img, label, pred, loss, acc = mnist.cnn()
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        feed, fetches = ["img", "label"], [loss, acc]
    elif name == "resnet":
        from paddle_tpu.models import resnet

        img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = resnet.resnet_cifar10(img, class_dim=10, depth=20)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(loss)
        feed, fetches = ["img", "label"], [loss]
    elif name == "transformer":
        from paddle_tpu.models import transformer

        src, tgt, lbl, cost = transformer.build(transformer.tiny_config(),
                                                src_len=16, tgt_len=16)
        feed = [src.name, tgt.name, lbl.name]
        fetches = [cost]
    else:
        raise SystemExit(f"unknown --model {name!r} "
                         f"(mlp|mnist_cnn|resnet|transformer)")
    return fluid.default_main_program(), feed, fetches


def _load_dir(dirname: str):
    import paddle_tpu.fluid as fluid

    exe = fluid.Executor(fluid.CPUPlace())
    program, feed_names, fetch_vars = fluid.io.load_inference_model(
        dirname, exe)
    return program, feed_names, fetch_vars


def cmd_lint(args) -> int:
    from . import verify_program

    if bool(args.dir) == bool(args.model):
        print("lint: pass exactly one of --dir or --model",
              file=sys.stderr)
        return 2
    if args.dir:
        program, feed, fetches = _load_dir(args.dir)
        kind = "lint"
    else:
        program, feed, fetches = _build_model(args.model)
        kind = "pe_run_steps" if args.mesh else "lint"
    report = verify_program(program, feed=feed, fetch_list=fetches,
                            mesh=args.mesh, kind=kind,
                            batch_hint=args.batch)
    if args.json:
        print(json.dumps({
            "kind": report.kind, "mesh": report.mesh,
            "duration_ms": round(report.duration_ms, 3),
            "errors": len(report.errors), "warns": len(report.warnings),
            "collective_bytes_est": report.collective_bytes_est,
            "memory_estimate": report.memory_estimate,
            "diagnostics": [d.to_dict() for d in report.diagnostics]}))
    else:
        print(report.format("info" if args.verbose else "warn"))
    if report.errors:
        return 1
    if args.strict and report.warnings:
        return 1
    return 0


def cmd_smoke() -> int:
    """CI round-trip: clean lint + one seeded defect caught, <2s."""
    import time

    from . import verify_program

    t0 = time.perf_counter()
    program, feed, fetches = _build_model("mlp")
    clean = verify_program(program, feed=feed, fetch_list=fetches)
    if clean.errors:
        print("smoke: FAIL — clean program reported errors:\n"
              + clean.format("error"))
        return 1
    # seed a dangling reference; the lint must name it
    gb = program.global_block()
    gb.append_op(type="elementwise_add",
                 inputs={"X": ["__no_such_var__"], "Y": [fetches[0]]},
                 outputs={"Out": [fetches[0].name]})
    seeded = verify_program(program, feed=feed, fetch_list=fetches)
    codes = {d.code for d in seeded.errors}
    if "AN104" not in codes:
        print(f"smoke: FAIL — seeded dangling ref not caught ({codes})")
        return 1
    print(f"smoke: ok — clean in {clean.duration_ms:.1f}ms, seeded "
          f"defect caught as AN104, total "
          f"{time.perf_counter() - t0:.2f}s")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m paddle_tpu.analysis",
                                description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI round-trip (<2s)")
    sub = p.add_subparsers(dest="cmd")
    lint = sub.add_parser("lint", help="verify a program statically")
    lint.add_argument("--dir", help="saved inference-model directory")
    lint.add_argument("--model",
                      help="in-tree builder: mlp|mnist_cnn|resnet|"
                           "transformer")
    lint.add_argument("--mesh", help="mesh spec to layout-check against, "
                                     "e.g. dp4,tp2")
    lint.add_argument("--batch", type=int, default=8,
                      help="batch placeholder for -1 dims (default 8)")
    lint.add_argument("--strict", action="store_true",
                      help="exit 1 on warnings too")
    lint.add_argument("--json", action="store_true")
    lint.add_argument("--verbose", action="store_true",
                      help="print info-severity notes too")
    args = p.parse_args(argv)
    if args.smoke:
        return cmd_smoke()
    if args.cmd == "lint":
        return cmd_lint(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
