"""Pass 1: static shape/dtype inference over a Program block.

Walks the ops of a block in order, propagating ``(shape, dtype)`` facts
from feeds / parameters / declared data vars through every op, using

 1. the explicit infer rule registered next to the op's dispatch entry
    (``ops.registry.INFER_REGISTRY`` — precise named diagnostics), else
 2. the generic abstract evaluator: ``jax.eval_shape`` over the op's
    registered forward impl with ``ShapeDtypeStruct`` operands — the same
    code the real trace runs, so anything traceable is inferable, else
 3. ``unknown`` (eager/data-dependent ops, control flow, LoD-dependent
    sequence kernels) — unknown facts propagate as unknown and never
    produce diagnostics, which is what keeps false positives at zero.

A mismatch surfaces as AN101 (shape) / AN102 (dtype) with the op index,
op type and operand var names — milliseconds instead of an XLA trace
error seconds into compile.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops import registry as _reg

# VarInfo: (shape tuple, dtype str) or None for statically-unknown.
VarInfo = Optional[Tuple[Tuple[int, ...], str]]

_SKIP_OPS = frozenset(["feed", "fetch", "read", "create_py_reader"])
_SIDE_EFFECT_OPS = frozenset(["print", "save", "save_combine"])

#: op families whose generic abstract evaluation can fail for reasons
#: other than a shape bug (host/LoD-dependent semantics) — their failures
#: demote to an info note instead of an AN101 error.
_UNRELIABLE_PREFIXES = ("sequence_", "lod_", "crf_", "beam_", "ctc_",
                        "warpctc", "linear_chain_crf", "chunk_eval",
                        "edit_distance", "im2sequence", "row_conv",
                        "dynamic_", "shrink_", "array_", "reorder_",
                        "multiclass_", "generate_", "rpn_", "box_",
                        "anchor_", "detection_", "polygon_", "roi_",
                        "prior_box", "density_prior_box", "target_assign",
                        "mine_hard_examples", "bipartite_match")


def _is_unreliable(op_type: str) -> bool:
    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    return base.startswith(_UNRELIABLE_PREFIXES)


def _is_eager(op_type: str) -> bool:
    from ..ops.array_ops import EAGER_OPS

    base = op_type[:-5] if op_type.endswith("_grad") else op_type
    return base in EAGER_OPS


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((str(k), _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return (v.shape, str(v.dtype), v.tobytes())
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


class _EvalCache:
    """Process-wide LRU over generic abstract evaluations, keyed on
    (op type, attrs, input signature) — repeated geometry (ResNet stages,
    transformer layers) infers once."""

    def __init__(self, cap: int = 4096):
        self.cap = cap
        self._od: "OrderedDict[tuple, object]" = OrderedDict()

    def get(self, key, miss):
        if key in self._od:
            self._od.move_to_end(key)
            return self._od[key]
        val = miss()
        self._od[key] = val
        if len(self._od) > self.cap:
            self._od.popitem(last=False)
        return val


_eval_cache = _EvalCache()


def _generic_eval(op, ins: Dict[str, List[VarInfo]], needs_rng: bool):
    """Abstractly evaluate one op via jax.eval_shape over its impl.

    Returns ({slot: [VarInfo]}, error_message_or_None, skipped_bool).
    ``skipped`` means the evaluation could not run for a reason that is
    NOT evidence of a user bug (host-dependent math, LoD semantics)."""
    import jax
    import jax.numpy as jnp

    opdef = _reg.get_op_def(op.type[:-5] if (not _reg.is_registered(op.type)
                                             and op.type.endswith("_grad"))
                            else op.type)
    if any(v is None for vals in ins.values() for v in vals):
        return {}, None, True

    structs = {slot: [jax.ShapeDtypeStruct(tuple(v[0]), np.dtype(v[1]))
                      for v in vals]
               for slot, vals in ins.items()}
    outputs_spec = {s: list(n) for s, n in op.outputs.items() if n}
    attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}

    def run():
        def absfn(vals, key):
            inputs = {slot: list(v) for slot, v in vals.items()}
            ctx = _reg.ExecContext(op.type, inputs, outputs_spec, op.attrs,
                                   [key] if needs_rng else None)
            raw = opdef.fn(ctx)
            out = {}
            for k, v in (raw or {}).items():
                if k.endswith("@LOD"):
                    continue
                out[k] = [x for x in (v if isinstance(v, (list, tuple))
                                      else [v])]
            return out

        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
        try:
            shaped = jax.eval_shape(absfn, structs, key_struct)
        except Exception as e:  # classified below
            return ("error", e)
        out = {}
        for slot, vals in shaped.items():
            out[slot] = [
                (tuple(int(d) for d in v.shape), str(np.dtype(v.dtype)))
                if hasattr(v, "shape") else None
                for v in vals]
        return ("ok", out)

    key = (op.type, _freeze(attrs),
           tuple(sorted((s, tuple((tuple(v[0]), v[1]) for v in vals))
                        for s, vals in ins.items())))
    kind, payload = _eval_cache.get(key, run)
    if kind == "ok":
        return payload, None, False
    exc = payload
    trace_errs = tuple(
        t for t in (getattr(jax.errors, n, None)
                    for n in ("ConcretizationTypeError",
                              "TracerArrayConversionError",
                              "TracerBoolConversionError",
                              "TracerIntegerConversionError"))
        if t is not None)
    if isinstance(exc, trace_errs) or isinstance(
            exc, (NotImplementedError, KeyError, AttributeError,
                  RuntimeError, IndexError)):
        return {}, None, True
    if _is_unreliable(op.type):
        return {}, None, True
    return {}, f"{type(exc).__name__}: {exc}", False


def _grad_mirror(op, env: Dict[str, VarInfo]) -> Dict[str, List[VarInfo]]:
    """Generic-vjp grad op: each output slot ``S@GRAD`` mirrors the
    forward input slot ``S`` (same shapes/dtypes — backward.py declares
    the grad vars that way too)."""
    out = {}
    for slot, names in op.outputs.items():
        if not slot.endswith("@GRAD"):
            out[slot] = [None] * len(names)
            continue
        fwd = op.inputs.get(slot[:-5], [])
        vals = []
        for i in range(len(names)):
            vals.append(env.get(fwd[i]) if i < len(fwd) and fwd[i] else None)
        out[slot] = vals
    return out


def run_infer_pass(program, block_idx, feed_infos: Dict[str, VarInfo],
                   diags: list, batch_hint: int = 8,
                   live=None) -> Dict[str, VarInfo]:
    """Infer shapes/dtypes through one block; appends Diagnostic records
    to ``diags``.  Returns the final name -> VarInfo environment.

    ``live``: op-index set from the structure pass — dead ops are skipped
    (the executor prunes them before tracing, so a dead op's shape bug is
    not a runtime error; the structure pass already notes it as AN106)."""
    from . import Diagnostic
    from ..fluid import control_flow_exec

    block = program.block(block_idx)

    def declared_info(name) -> VarInfo:
        if not block._has_var_recursive(name):
            return None
        v = block._var_recursive(name)
        if v.shape is None or v.dtype is None:
            return None
        shape = tuple(batch_hint if d in (-1, None) else int(d)
                      for d in v.shape)
        try:
            dt = str(np.dtype(v.dtype))
        except TypeError:
            return None
        return (shape, dt)

    env: Dict[str, VarInfo] = {}
    for name, info in feed_infos.items():
        env[name] = info
        # a fed array must agree with the declared var on every static dim
        if info is None or not block._has_var_recursive(name):
            continue
        v = block._var_recursive(name)
        if v.shape is None:
            continue
        want = tuple(v.shape)
        got = info[0]
        # rank mismatch is legal (the mul family flattens, and feeders
        # reshape); LoD feeds bind the ragged leading dim to the packed
        # row count — only same-rank static-dim disagreements are bugs
        ok = len(got) != len(want) or all(
            w in (-1, None) or int(w) == g for w, g in zip(want, got))
        if ok is not True and getattr(v, "lod_level", 0) > 0:
            ok = True
        if not ok:
            # warn, not error: this framework binds shapes at trace time
            # from the fed arrays (framework.py module contract), and the
            # v2 facade feeds index labels into class-dim-declared data
            # vars on purpose — a disagreement is a smell, not a fault
            diags.append(Diagnostic(
                "AN101", "warn",
                f"feed '{name}' shape {list(got)} does not match declared "
                f"var shape {list(want)}",
                var=name, hint="fix the fed array or the data layer shape"))

    def resolve(name) -> VarInfo:
        if name in env:
            return env[name]
        # first read of a non-fed name: persistables and data vars carry
        # trustworthy declared shapes; everything else is unknown
        if block._has_var_recursive(name):
            v = block._var_recursive(name)
            if v.persistable or getattr(v, "is_data", False):
                info = declared_info(name)
                env[name] = info
                return info
        env[name] = None
        return None

    for idx, op in enumerate(block.ops):
        if live is not None and idx not in live:
            for names in op.outputs.values():
                for n in names:
                    if n:
                        env[n] = None
            continue
        if op.type in _SKIP_OPS or op.type in _SIDE_EFFECT_OPS:
            for names in op.outputs.values():
                for n in names:
                    if n:
                        env[n] = declared_info(n)
            continue
        if (op.type in control_flow_exec.HANDLERS or _is_eager(op.type)
                or op.attr("sub_block") is not None):
            # data-dependent / control-flow: outputs unknown, no claims
            for names in op.outputs.values():
                for n in names:
                    if n:
                        env[n] = None
            continue

        ins = {slot: [resolve(n) if n else None for n in names]
               for slot, names in op.inputs.items()}

        is_grad = (not _reg.is_registered(op.type)) \
            and op.type.endswith("_grad") \
            and _reg.is_registered(op.type[:-5])
        rule = _reg.get_infer_rule(op.type)
        outs: Dict[str, List[VarInfo]] = {}
        if rule is not None:
            try:
                outs = rule(op, ins) or {}
            except _reg.InferMismatch as m:
                diags.append(Diagnostic(
                    m.code, "error", str(m), op_idx=idx, op_type=op.type,
                    hint="operand shapes/dtypes are inconsistent at build "
                         "time; this would fail (or silently truncate) in "
                         "compile"))
                outs = {}
        elif is_grad and _reg.get_op_def(op.type[:-5]).grad_fn is None:
            outs = _grad_mirror(op, env)
        elif _reg.is_registered(op.type) or is_grad:
            opdef = _reg.get_op_def(op.type[:-5] if is_grad else op.type)
            if is_grad:
                outs = _grad_mirror(op, env)
            else:
                outs, err, skipped = _generic_eval(op, ins,
                                                   opdef.stateful)
                if err is not None:
                    opnd = ", ".join(
                        f"{n}={list(v[0]) if v else '?'}"
                        for ns in op.inputs.values() for n, v in
                        ((n, env.get(n)) for n in ns) if n)
                    diags.append(Diagnostic(
                        "AN101", "error",
                        f"{op.type}: abstract evaluation failed — {err} "
                        f"(operands: {opnd})",
                        op_idx=idx, op_type=op.type,
                        hint="operand shapes are inconsistent; the XLA "
                             "trace would fail the same way after seconds "
                             "of compile"))
        # unknown op types: the structure pass owns that diagnostic

        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            for i, n in enumerate(names):
                if not n:
                    continue
                env[n] = vals[i] if vals is not None and i < len(vals) \
                    else None
    return env
