"""Pass 0: structural/dataflow checks over one block (no shape math).

Catches the misuse classes that today surface as opaque runtime errors
deep inside trace/compile: unknown op types (NotImplementedError mid-
trace), dangling references (KeyError / 'not initialized'), def-before-
use reads, fetches nothing produces, unused feeds, and dead ops.  All
reported with op index + var names, in milliseconds.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..ops import registry as _reg
from .infer import _SIDE_EFFECT_OPS, _SKIP_OPS


def _is_known_op(op_type: str) -> bool:
    from ..fluid import control_flow_exec

    if op_type in _SKIP_OPS or op_type in _SIDE_EFFECT_OPS:
        return True
    if op_type in control_flow_exec.HANDLERS:
        return True
    if _reg.is_registered(op_type):
        return True
    return op_type.endswith("_grad") and _reg.is_registered(op_type[:-5])


def live_op_indices(block, feed_names: Sequence[str],
                    fetch_names: Sequence[str]) -> Set[int]:
    """The executor's live-op slice (BlockPlan rule): ops needed for the
    fetches, persistable writes, or side effects."""

    def _persistable(name):
        return block._has_var_recursive(name) and \
            block._var_recursive(name).persistable

    needed = set(fetch_names)
    live: Set[int] = set()
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if op.type in _SKIP_OPS:
            continue
        outs = [n for n in op.output_arg_names if n]
        if (op.type in _SIDE_EFFECT_OPS
                or any(n in needed for n in outs)
                or any(_persistable(n) for n in outs)):
            live.add(idx)
            needed.update(n for n in op.input_arg_names if n)
    return live


def run_structure_pass(program, block_idx, feed_names: Sequence[str],
                       fetch_names: Sequence[str], diags: list) -> Set[int]:
    """Append structural diagnostics; returns the live-op index set."""
    from . import Diagnostic

    block = program.block(block_idx)
    feed_set = set(feed_names)
    fetch_set = set(fetch_names)
    live = live_op_indices(block, feed_names, fetch_names)

    def _var(name):
        return block._var_recursive(name) \
            if block._has_var_recursive(name) else None

    # one forward walk: where is every name first written?
    first_write: Dict[str, int] = {}
    consumed: Set[str] = set()
    for idx, op in enumerate(block.ops):
        for n in op.output_arg_names:
            if n and n not in first_write:
                first_write[n] = idx

    for idx, op in enumerate(block.ops):
        if op.type in _SKIP_OPS:
            continue
        if not _is_known_op(op.type):
            diags.append(Diagnostic(
                "AN109", "error" if idx in live else "info",
                f"unknown op type '{op.type}' (op #{idx}): no registered "
                f"TPU implementation",
                op_idx=idx, op_type=op.type,
                hint="register the op in paddle_tpu/ops or remove it; a "
                     "live unknown op raises NotImplementedError mid-"
                     "trace" if idx in live else
                     "dead — the executor prunes it, but it is likely a "
                     "build mistake"))
        for name in op.input_arg_names:
            if not name:
                continue
            consumed.add(name)
            if name in feed_set:
                continue
            wr = first_write.get(name)
            v = _var(name)
            persistable = v is not None and v.persistable
            is_data = v is not None and getattr(v, "is_data", False)
            if wr is None or wr >= idx:
                # read before any in-block write
                if persistable or is_data:
                    continue  # scope state / fed-at-run data: fine
                if v is None and wr is None:
                    diags.append(Diagnostic(
                        "AN104", "error" if idx in live else "info",
                        f"op #{idx} ({op.type}) reads '{name}' which no "
                        f"op produces and no block declares",
                        op_idx=idx, op_type=op.type, var=name,
                        hint="dangling reference — typo'd var name in the "
                             "op's inputs?"))
                elif wr is not None and wr > idx:
                    diags.append(Diagnostic(
                        "AN103", "warn",
                        f"op #{idx} ({op.type}) reads '{name}' before op "
                        f"#{wr} writes it (def-before-use)",
                        op_idx=idx, op_type=op.type, var=name,
                        hint="the first run will fault with 'not "
                             "initialized' unless the scope was seeded "
                             "externally"))
                else:
                    diags.append(Diagnostic(
                        "AN105", "warn" if idx in live else "info",
                        f"op #{idx} ({op.type}) reads '{name}' which is "
                        f"declared (non-persistable) but never written "
                        f"in-block",
                        op_idx=idx, op_type=op.type, var=name,
                        hint="runs only if the scope is pre-seeded; mark "
                             "the var persistable or feed it"))

    # dead ops (relative to THESE fetches): info — normal for mixed
    # train/eval programs, but the first place to look when a fetch is
    # mysteriously constant
    for idx, op in enumerate(block.ops):
        if op.type in _SKIP_OPS or idx in live:
            continue
        diags.append(Diagnostic(
            "AN106", "info",
            f"op #{idx} ({op.type}) is dead for fetches "
            f"{sorted(fetch_set) if fetch_set else '[]'} (outputs "
            f"unconsumed, non-persistable, unfetched)",
            op_idx=idx, op_type=op.type))

    # unused feeds
    for name in sorted(feed_set):
        if name not in consumed and name not in fetch_set:
            diags.append(Diagnostic(
                "AN107", "warn",
                f"feed '{name}' is consumed by no op in block "
                f"{block_idx}",
                var=name,
                hint="misspelled feed key, or feeding an eval-only input "
                     "to a train program?"))

    # fetches nothing can produce
    for name in sorted(fetch_set):
        v = _var(name)
        ok = (name in first_write or name in feed_set
              or (v is not None and v.persistable))
        if not ok:
            diags.append(Diagnostic(
                "AN108", "error",
                f"fetch '{name}' is produced by no op, not fed, and not "
                f"persistable",
                var=name,
                hint="misspelled fetch target? the trace would fail with "
                     "a bare KeyError"))
    return live
