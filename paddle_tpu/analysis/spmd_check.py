"""Pass 2: static SPMD layout checking against a named mesh.

Works from the mesh's {axis: extent} map alone (no devices needed — a
laptop can lint a dp256 pod program), mirroring the canonical
``parallel.spmd`` layout rules: feed batches must divide the data axes
(the runtime ``place_feed`` check, now pre-compile), parameter dims
annotated onto a mesh axis (``dist_spec``/``dist_hint`` or the
SpecLayout column/row alternation) are checked for divisibility, shared
weights with conflicting column/row chain positions are flagged, and a
pre-compile collective-bytes estimate lands on the
``analysis.collective_bytes_est`` gauge next to the post-compile
``spmd.collective_bytes`` truth gauge.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..fluid.framework import Parameter, Program


def mesh_axes_of(mesh) -> Dict[str, int]:
    """{axis: extent} from a Mesh, a 'dp4,tp2' spec string, or a dict."""
    if mesh is None:
        return {}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    if isinstance(mesh, str):
        from ..parallel.mesh import parse_mesh_spec

        return parse_mesh_spec(mesh)
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def _axes_label(axes: Dict[str, int]) -> str:
    return "x".join(f"{a}{n}" for a, n in axes.items()) or "none"


def _dtype_bytes(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 4


def _chain(program: Program):
    """Every mul/matmul/lookup_table weight consumption in block order:
    [(op_idx, op_type, weight_name, chain_order)] — the Megatron
    column/row alternation index the spec table derives from."""
    out = []
    order = 0
    for idx, op in enumerate(program.global_block().ops):
        if op.type == "lookup_table":
            for n in op.inputs.get("W", []):
                if n:
                    out.append((idx, op.type, n, None))
        elif op.type in ("mul", "matmul"):
            for n in op.inputs.get("Y", []):
                if n:
                    out.append((idx, op.type, n, order))
                    order += 1
    return out


def run_spmd_pass(program: Program, axes: Dict[str, int],
                  feed_infos: Dict[str, object], fetch_names, diags: list,
                  batch_concrete: bool) -> Optional[int]:
    """Append mesh diagnostics; returns the collective-bytes estimate
    (None when the mesh has no sharding axes)."""
    from . import Diagnostic

    if not axes or all(n <= 1 for n in axes.values()):
        return None
    label = _axes_label(axes)
    gb = program.global_block()
    dp = axes.get("dp", 1)
    tp = axes.get("tp", axes.get("mp", 1))
    fsdp = axes.get("fsdp", 1)

    # 1. feed batches must divide the data axis (place_feed, pre-compile)
    if dp > 1 and batch_concrete:
        for name, info in sorted(feed_infos.items()):
            if info is None or not info[0]:
                continue
            b = int(info[0][0])
            if b % dp != 0:
                diags.append(Diagnostic(
                    "AN201", "error",
                    f"feed '{name}' batch {b} is not divisible by the "
                    f"mesh data axis (dp={dp}, mesh {label})",
                    var=name,
                    hint=f"pad or drop the short batch, or pick a global "
                         f"batch that is a multiple of {dp} — the sharded "
                         f"window would reject this at dispatch"))

    # 2. annotated parameter dims must divide their mesh axis
    chain = _chain(program)
    roles: Dict[str, list] = {}
    for idx, op_type, name, order in chain:
        roles.setdefault(name, []).append((idx, op_type, order))

    def check_dims(name, shape, spec, source):
        for d, ax in enumerate(spec):
            if ax is None or d >= len(shape):
                continue
            ext = axes.get(ax, 0)
            if ext <= 1:
                continue  # axis absent/trivial: degrades by design
            if shape[d] is None or int(shape[d]) % ext != 0:
                diags.append(Diagnostic(
                    "AN202", "warn",
                    f"param '{name}' dim {d} ({shape[d]}) does not divide "
                    f"mesh axis {ax}={ext} ({source}); it will run "
                    f"REPLICATED on that axis",
                    var=name,
                    hint="resize the dim to a multiple of the axis or "
                         "drop the annotation — silent degradation costs "
                         "the sharding you asked for"))

    for v in gb.vars.values():
        if not isinstance(v, Parameter) or v.shape is None:
            continue
        shape = tuple(v.shape)
        ds = getattr(v, "dist_spec", None)
        if ds is not None:
            check_dims(v.name, shape, tuple(ds[: len(shape)]),
                       "explicit dist_spec")
            continue
        dh = getattr(v, "dist_hint", None)
        if dh is not None:
            check_dims(v.name, shape, (dh,) + (None,) * (len(shape) - 1),
                       "explicit dist_hint")
            continue
        uses = roles.get(v.name)
        if uses is None or len(shape) != 2 or (tp <= 1 and fsdp <= 1):
            continue
        # canonical SpecLayout: embedding/even orders column P(fsdp, tp),
        # odd orders row P(tp, fsdp)
        order = uses[0][2]
        if order is None or order % 2 == 0:
            spec = ("fsdp" if fsdp > 1 else None, "tp" if tp > 1 else None)
        else:
            spec = ("tp" if tp > 1 else None, "fsdp" if fsdp > 1 else None)
        check_dims(v.name, shape, spec, "canonical SpecLayout table")

    # 3. column/row conflicts: one weight at both chain parities (or as
    # embedding AND linear operand) gets ONE layout — the other use pays
    # a resharding collective every step
    if tp > 1 or fsdp > 1:
        for name, uses in sorted(roles.items()):
            kinds = {("embedding" if o is None else ("col" if o % 2 == 0
                                                     else "row"))
                     for _, _, o in uses}
            if len(kinds) > 1:
                sites = ", ".join(f"op #{i} ({t})" for i, t, _ in uses)
                diags.append(Diagnostic(
                    "AN203", "warn",
                    f"weight '{name}' is consumed at conflicting layout "
                    f"positions ({'+'.join(sorted(kinds))}: {sites}) on "
                    f"mesh {label}",
                    var=name,
                    hint="the spec table assigns the FIRST use's layout; "
                         "every other use inserts a resharding collective "
                         "— split the weight or align the uses"))

    # 4. pre-compile collective estimate (cross-check against the
    # post-compile spmd.collective_bytes gauge)
    est = 0
    if dp > 1:
        # gradient all-reduce: one full param-sized reduction per step
        # falls out of the partitioned backward when training
        if program._params_grads is not None:
            for v in gb.vars.values():
                if isinstance(v, Parameter) and v.shape:
                    est += int(np.prod(v.shape, dtype=np.int64)) \
                        * _dtype_bytes(v.dtype)
    if tp > 1:
        # row-parallel (odd-order) matmuls all-reduce their activation
        # output [batch, d_out] once per consumption
        for idx, op_type, name, order in chain:
            if order is None or order % 2 == 0:
                continue
            v = gb.vars.get(name)
            if v is None or not v.shape or len(v.shape) != 2:
                continue
            batch = 1
            for info in feed_infos.values():
                if info is not None and info[0]:
                    batch = max(batch, int(info[0][0]))
            est += batch * int(v.shape[1]) * _dtype_bytes(v.dtype)
    if est:
        diags.append(Diagnostic(
            "AN204", "info",
            f"estimated per-step collective traffic on mesh {label}: "
            f"{est} bytes (grad all-reduce + row-parallel activation "
            f"all-reduce)", hint="compare with the spmd.collective_bytes "
            "gauge after compile"))
        try:
            from .. import observe

            observe.registry().set_gauge(
                "analysis.collective_bytes_est", float(est),
                labels={"mesh": label})
        except Exception:
            pass
    return est
