"""paddle_tpu.analysis — the pre-compile program verifier (ISSUE 8).

The reference ships a whole ``fluid/inference/analysis`` subsystem because
program-as-IR frameworks need static checks before an expensive backend
touches the graph.  Here every misuse used to surface as an opaque XLA
trace error seconds into compile, or as a scattered runtime reject — this
package fails in *milliseconds* with named diagnostics instead.

Four pass families over a Program (plus optional mesh + jit-config
context), run by :func:`verify_program`:

 - **structure** (AN103-AN109): dangling refs, def-before-use, unknown
   ops, dead ops, unused feeds, unproducible fetches;
 - **shape/dtype inference** (AN101/AN102): per-op infer rules registered
   next to the op dispatch table + generic abstract evaluation via
   ``jax.eval_shape`` over the op impls;
 - **SPMD layout** (AN201-AN204): mesh-divisibility of feed batches and
   annotated param dims, column/row chain conflicts, pre-compile
   collective-bytes estimate;
 - **contracts** (AN301/AN302, AN401/AN402): donation hazards and the
   fp16-loss-scale / eager-window runtime rejects, pre-compile;
 - **memcheck** (AN501-AN503): pre-flight peak-HBM estimate from the same
   shape facts (params + optimizer slots + activation high-water,
   donation-aware, shard-divided), diagnosed against
   ``PADDLE_MEM_BUDGET_MB`` and cross-checked against the compiled
   ``memory.peak_bytes`` truth gauge (``observe.memory``).

Execution wiring: ``Executor.run``/``run_steps`` and ``ParallelExecutor``
call :func:`check_before_compile` on every jit-cache miss, gated by
``PADDLE_TPU_VERIFY=warn|strict|off`` (default ``warn``: error-severity
findings become Python warnings; ``strict`` raises :class:`VerifyError`
before any trace).  Diagnostics flow into ``observe`` events and
``analysis.*`` counters.  CLI: ``python -m paddle_tpu.analysis lint``.
Catalog: docs/ANALYSIS.md.
"""

from __future__ import annotations

import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["Diagnostic", "Report", "VerifyError", "verify_program",
           "check_before_compile", "verify_mode", "SEVERITIES", "CODES"]

SEVERITIES = ("error", "warn", "info")

#: the diagnostic catalog (code -> one-line meaning); docs/ANALYSIS.md
#: carries the long-form table
CODES = {
    "AN000": "verifier internal error (diagnostic-free pass skipped)",
    "AN101": "static shape mismatch",
    "AN102": "static dtype mismatch (integer-index input fed floats)",
    "AN103": "def-before-use read",
    "AN104": "dangling reference (undeclared, never-produced input)",
    "AN105": "maybe-uninitialized read (declared, never written)",
    "AN106": "dead op for the requested fetches",
    "AN107": "unused feed",
    "AN108": "fetch nothing produces",
    "AN109": "unknown op type",
    "AN201": "feed batch not divisible by mesh data axis",
    "AN202": "annotated param dim not divisible by its mesh axis",
    "AN203": "conflicting column/row layout positions for one weight",
    "AN204": "pre-compile collective-bytes estimate",
    "AN301": "optimizer ops mutate shared state in an inference program",
    "AN302": "fetch aliases donated training state",
    "AN401": "fp16 loss-scale program on the per-step PE path",
    "AN402": "data-dependent eager ops inside a fused window",
    "AN501": "pre-flight peak-HBM estimate",
    "AN502": "estimated peak HBM exceeds PADDLE_MEM_BUDGET_MB",
    "AN503": "estimated peak HBM within 10% of PADDLE_MEM_BUDGET_MB",
}


@dataclass
class Diagnostic:
    code: str
    severity: str                     # error | warn | info
    message: str
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None
    block_idx: int = 0

    def format(self) -> str:
        site = ""
        if self.op_idx is not None:
            site = f" @op#{self.op_idx}" + (f"({self.op_type})"
                                            if self.op_type else "")
        elif self.var:
            site = f" @var '{self.var}'"
        s = f"[{self.code}:{self.severity}]{site} {self.message}"
        if self.hint:
            s += f"  (hint: {self.hint})"
        return s

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


@dataclass
class Report:
    diagnostics: List[Diagnostic] = field(default_factory=list)
    duration_ms: float = 0.0
    kind: str = "run"
    mesh: Optional[str] = None
    collective_bytes_est: Optional[int] = None
    #: the AN5xx pre-flight peak-HBM estimate (memcheck pass), or None
    memory_estimate: Optional[dict] = None

    def by_severity(self, severity: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.by_severity("error")

    @property
    def warnings(self) -> List[Diagnostic]:
        return self.by_severity("warn")

    @property
    def clean(self) -> bool:
        """No errors and no warnings (info notes allowed)."""
        return not self.errors and not self.warnings

    def format(self, min_severity: str = "info") -> str:
        keep = SEVERITIES[: SEVERITIES.index(min_severity) + 1]
        lines = [d.format() for d in self.diagnostics if d.severity in keep]
        lines.append(
            f"-- verify[{self.kind}]: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.by_severity('info'))} note(s) in "
            f"{self.duration_ms:.1f}ms --")
        return "\n".join(lines)


class VerifyError(RuntimeError):
    """Strict-mode verification failure, raised BEFORE any trace/compile."""

    def __init__(self, report: Report):
        self.report = report
        errs = report.errors
        head = f"program verification failed: {len(errs)} error(s)"
        super().__init__(
            head + "\n" + "\n".join(d.format() for d in errs))


def verify_mode() -> str:
    from ..fluid import envcontract

    return envcontract.get("PADDLE_TPU_VERIFY")


def _feed_infos(program, feed, batch_hint):
    """Normalize the feed argument into name -> (shape, dtype) facts.

    Accepts a dict of arrays (executor path: concrete) or a list of feed
    NAMES / None (static path: declared shapes with the batch placeholder
    bound to ``batch_hint``).  Returns (infos, concrete_flag)."""
    import numpy as np

    gb = program.global_block()
    infos: Dict[str, object] = {}
    if isinstance(feed, dict):
        for k, v in feed.items():
            try:
                arr = v if hasattr(v, "shape") and hasattr(v, "dtype") \
                    else np.asarray(v)
                infos[k] = (tuple(int(d) for d in arr.shape),
                            str(np.dtype(arr.dtype)))
            except Exception:
                infos[k] = None
        return infos, True
    names = list(feed) if feed is not None else [
        v.name for v in gb.vars.values() if getattr(v, "is_data", False)]
    for k in names:
        if gb._has_var_recursive(k):
            v = gb._var_recursive(k)
            if v.shape is not None:
                try:
                    infos[k] = (
                        tuple(batch_hint if d in (-1, None) else int(d)
                              for d in v.shape),
                        str(np.dtype(v.dtype)))
                    continue
                except TypeError:
                    pass
        infos[k] = None
    return infos, False


def verify_program(program=None, feed=None, fetch_list=None, mesh=None,
                   kind: str = "run", batch_hint: int = 8,
                   block_idx: int = 0) -> Report:
    """Run all static passes over ``program``; never compiles anything.

    ``feed``: dict of (arrays|shapes) for concrete checking, or a list of
    feed names / None for declared-shape mode (``-1`` batch dims bind to
    ``batch_hint``).  ``mesh``: a Mesh, a ``"dp4,tp2"`` spec string, or an
    {axis: extent} dict — enables the SPMD pass.  ``kind`` names the
    execution surface the program is headed for (``run``, ``run_steps``,
    ``pe_run``, ``pe_run_steps``, ``lint``) and selects the contract
    checks.
    """
    from ..fluid.framework import Variable, default_main_program
    from .contracts import run_contract_pass
    from .infer import run_infer_pass
    from .spmd_check import mesh_axes_of, run_spmd_pass, _axes_label
    from .structure import run_structure_pass

    program = program or default_main_program()
    fetch_names = [f.name if isinstance(f, Variable) else str(f)
                   for f in (fetch_list or [])]
    t0 = time.perf_counter()
    diags: List[Diagnostic] = []
    axes = mesh_axes_of(mesh)
    if axes.get("dp", 0) > 1:
        # keep the declared-shape placeholder dividable so static lint
        # doesn't invent indivisible batches
        batch_hint = max(batch_hint, axes["dp"] * 2)
        if batch_hint % axes["dp"]:
            batch_hint = axes["dp"] * 2
    feed_infos, concrete = _feed_infos(program, feed, batch_hint)

    def guarded(pass_fn, *args):
        try:
            return pass_fn(*args)
        except Exception as e:  # a verifier bug must not fail the run
            diags.append(Diagnostic(
                "AN000", "info",
                f"verifier pass {pass_fn.__name__} crashed: "
                f"{type(e).__name__}: {e}"))
            return None

    live = guarded(run_structure_pass, program, block_idx,
                   list(feed_infos), fetch_names, diags)
    env = guarded(run_infer_pass, program, block_idx, feed_infos, diags,
                  batch_hint, live)
    est = guarded(run_spmd_pass, program, axes, feed_infos, fetch_names,
                  diags, concrete)
    guarded(run_contract_pass, program, fetch_names, kind, diags)
    from .memcheck import run_memcheck_pass

    mem_est = guarded(run_memcheck_pass, program, block_idx, env or {},
                      axes, feed_infos, fetch_names, diags, batch_hint)

    order = {s: i for i, s in enumerate(SEVERITIES)}
    diags.sort(key=lambda d: (order.get(d.severity, 9),
                              d.op_idx if d.op_idx is not None else 1 << 30))
    return Report(diagnostics=diags,
                  duration_ms=(time.perf_counter() - t0) * 1e3,
                  kind=kind, mesh=_axes_label(axes) if axes else None,
                  collective_bytes_est=est, memory_estimate=mem_est)


# -- executor integration ---------------------------------------------------

# one verification per (program identity, jit config); re-verifying the
# same compiled entry would only re-pay the walk.  Lock-protected: the
# serving engine compiles from worker threads (tools/repo_lint.py's
# racy-dict contract).
_verified: Dict[tuple, bool] = {}
_warned: set = set()
_memo_lock = threading.Lock()


def reset() -> None:
    """Clear the once-per-program memoization (test-harness hook)."""
    with _memo_lock:
        _verified.clear()
        _warned.clear()


def check_before_compile(program, feed=None, fetch_list=None, mesh=None,
                         kind: str = "run") -> Optional[Report]:
    """The Executor/ParallelExecutor hook: verify on jit-cache miss.

    ``PADDLE_TPU_VERIFY=off`` skips entirely; ``warn`` (default) turns
    error findings into Python warnings; ``strict`` raises
    :class:`VerifyError` before any trace.  Every outcome lands on the
    ``analysis.*`` counters and (when configured) the observe event log.
    """
    mode = verify_mode()
    if mode == "off":
        return None
    try:
        from ..parallel.mesh import mesh_label

        label = mesh_label(mesh) if mesh is not None \
            and not isinstance(mesh, (str, dict)) else str(mesh or "")
        fetch_sig = tuple(str(getattr(f, "name", f))
                          for f in (fetch_list or []))
        feed_sig = tuple(sorted(feed)) if isinstance(feed, dict) \
            else tuple(feed or ())
        key = (program._cache_token, program._version, kind, label,
               fetch_sig, feed_sig, mode)
        with _memo_lock:
            if _verified.get(key):
                return None
        report = verify_program(program, feed=feed, fetch_list=fetch_list,
                                mesh=mesh, kind=kind)
        with _memo_lock:
            _verified[key] = True
            if len(_verified) > 4096:
                _verified.clear()
        _note(report)
    except VerifyError:
        raise
    except Exception:
        return None  # the verifier must never take the run down
    if report.errors:
        if mode == "strict":
            raise VerifyError(report)
        wkey = (program._cache_token,
                tuple(sorted({d.code for d in report.errors})))
        with _memo_lock:
            fresh = wkey not in _warned
            _warned.add(wkey)
        if fresh:
            warnings.warn(
                "program verification found "
                f"{len(report.errors)} error(s) "
                f"(PADDLE_TPU_VERIFY=strict to fail fast):\n"
                + "\n".join(d.format() for d in report.errors),
                stacklevel=3)
    return report


def _note(report: Report) -> None:
    """analysis.* counters + one observe event per verification."""
    try:
        from .. import observe

        reg = observe.registry()
        reg.inc("analysis.programs")
        reg.record_timing("analysis.verify_ms", report.duration_ms / 1e3)
        for d in report.diagnostics:
            reg.inc("analysis.diagnostics",
                    labels={"code": d.code, "severity": d.severity})
        if report.diagnostics:
            observe.emit(
                "analysis.verify", kind=report.kind, mesh=report.mesh,
                errors=len(report.errors), warns=len(report.warnings),
                notes=len(report.by_severity("info")),
                ms=round(report.duration_ms, 3),
                codes=sorted({d.code for d in report.diagnostics}))
    except Exception:
        pass
