"""Data-plane state blobs under the checkpoint ``_SUCCESS`` protocol.

One small JSON blob per host rank (``data_state_<rank>.json``), written
into the STAGED serial directory before its ``_SUCCESS`` marker is
committed — so iterator position and model state are one atomic unit:
either both survive a kill or neither does, and the serial scroll-delete
prunes them together.  Wired into both checkpoint writers:

 - ``fluid.trainer.save_checkpoint(data_state=...)`` (single-host serial
   dirs) and ``load_checkpoint`` — which treats an unreadable blob like
   an unreadable param file and FALLS BACK to the previous complete
   serial (a corrupt cursor silently resuming at the wrong sample is the
   exact failure this subsystem exists to kill);
 - ``parallel.multihost.save_sharded_serial(data_state=...)`` — every
   process writes its own rank's blob before the all-writers barrier, so
   process 0's ``_SUCCESS`` covers the whole fleet's data plane.

``PADDLE_FAULT_SHARD_CORRUPT=1`` truncates the next write (one-shot):
the deterministic oracle for the fallback path.
"""

from __future__ import annotations

import json
import os
from typing import Optional

__all__ = ["DATA_STATE_PREFIX", "data_state_path", "save_data_state",
           "load_data_state"]

DATA_STATE_PREFIX = "data_state_"
_VERSION = 1


def data_state_path(dirname: str, rank: int) -> str:
    return os.path.join(dirname, f"{DATA_STATE_PREFIX}{int(rank)}.json")


def save_data_state(dirname: str, state: dict, rank: int = 0) -> str:
    """Write one rank's iterator-state blob into a staged serial dir.

    tmp + rename so a concurrent reader never sees a torn write; the blob
    only becomes trusted when the CALLER commits the dir's ``_SUCCESS``
    marker.  Consults the shard-corrupt fault hook (truncated payload)
    so tests can deterministically exercise the load-side fallback."""
    from ..fluid import fault as _fault

    payload = json.dumps({"version": _VERSION, "rank": int(rank),
                          "state": state})
    if _fault.shard_corrupt():
        payload = payload[:max(1, len(payload) // 2)]
    path = data_state_path(dirname, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    _fault.io_delay()
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def load_data_state(dirname: str, rank: int = 0) -> Optional[dict]:
    """Read one rank's blob from a COMMITTED serial dir.

    Returns ``None`` when the serial simply has no data state (a
    checkpoint from before this subsystem, or a resume onto a rank the
    save never had) — the caller falls back to legacy sample-skip
    replay.  Raises ``IOError`` when a blob EXISTS but cannot be read
    (truncation, version drift): the caller must treat the whole serial
    as unreadable and fall back to the previous complete one, exactly
    like a corrupt param file."""
    path = data_state_path(dirname, rank)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
        version = int(payload["version"])
        state = payload["state"]
    except (ValueError, KeyError, TypeError) as exc:
        raise IOError(
            f"data_state blob {path} is unreadable ({exc!r}) — treating "
            f"this serial as corrupt") from exc
    if version != _VERSION:
        raise IOError(
            f"data_state blob {path} has version {version}, this build "
            f"reads {_VERSION}")
    return state
