"""Data-plane state blobs under the checkpoint ``_SUCCESS`` protocol.

One small JSON blob per host rank (``data_state_<rank>.json``), written
into the STAGED serial directory before its ``_SUCCESS`` marker is
committed — so iterator position and model state are one atomic unit:
either both survive a kill or neither does, and the serial scroll-delete
prunes them together.  Wired into both checkpoint writers:

 - ``fluid.trainer.save_checkpoint(data_state=...)`` (single-host serial
   dirs) and ``load_checkpoint`` — which treats an unreadable blob like
   an unreadable param file and FALLS BACK to the previous complete
   serial (a corrupt cursor silently resuming at the wrong sample is the
   exact failure this subsystem exists to kill);
 - ``parallel.multihost.save_sharded_serial(data_state=...)`` — every
   process writes its own rank's blob before the all-writers barrier, so
   process 0's ``_SUCCESS`` covers the whole fleet's data plane.

``PADDLE_FAULT_SHARD_CORRUPT=1`` truncates the next write (one-shot):
the deterministic oracle for the fallback path.
"""

from __future__ import annotations

import json
import os
from typing import Optional

__all__ = ["DATA_STATE_PREFIX", "data_state_path", "save_data_state",
           "load_data_state", "load_all_data_states", "remap_data_state"]

DATA_STATE_PREFIX = "data_state_"
_VERSION = 1


def data_state_path(dirname: str, rank: int) -> str:
    return os.path.join(dirname, f"{DATA_STATE_PREFIX}{int(rank)}.json")


def save_data_state(dirname: str, state: dict, rank: int = 0) -> str:
    """Write one rank's iterator-state blob into a staged serial dir.

    tmp + rename so a concurrent reader never sees a torn write; the blob
    only becomes trusted when the CALLER commits the dir's ``_SUCCESS``
    marker.  Consults the shard-corrupt fault hook (truncated payload)
    so tests can deterministically exercise the load-side fallback."""
    from ..fluid import fault as _fault

    payload = json.dumps({"version": _VERSION, "rank": int(rank),
                          "state": state})
    if _fault.shard_corrupt():
        payload = payload[:max(1, len(payload) // 2)]
    path = data_state_path(dirname, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    _fault.io_delay()
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def load_data_state(dirname: str, rank: int = 0) -> Optional[dict]:
    """Read one rank's blob from a COMMITTED serial dir.

    Returns ``None`` when the serial simply has no data state (a
    checkpoint from before this subsystem, or a resume onto a rank the
    save never had) — the caller falls back to legacy sample-skip
    replay.  Raises ``IOError`` when a blob EXISTS but cannot be read
    (truncation, version drift): the caller must treat the whole serial
    as unreadable and fall back to the previous complete one, exactly
    like a corrupt param file."""
    path = data_state_path(dirname, rank)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
        version = int(payload["version"])
        state = payload["state"]
    except (ValueError, KeyError, TypeError) as exc:
        raise IOError(
            f"data_state blob {path} is unreadable ({exc!r}) — treating "
            f"this serial as corrupt") from exc
    if version != _VERSION:
        raise IOError(
            f"data_state blob {path} has version {version}, this build "
            f"reads {_VERSION}")
    return state


def load_all_data_states(dirname: str) -> dict:
    """Every rank's blob from a COMMITTED serial dir: ``rank -> state``.

    The reshard-on-load path needs the WHOLE dead fleet's cursors (a
    dp4 serial resumed on dp2 merges two shard streams per new rank),
    not just this rank's.  Empty dict = legacy serial with no data
    plane; a blob that exists but cannot be read raises ``IOError``
    exactly like :func:`load_data_state` — the caller condemns the
    serial."""
    out = {}
    try:
        names = os.listdir(dirname)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(DATA_STATE_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            rank = int(name[len(DATA_STATE_PREFIX):-len(".json")])
        except ValueError:
            continue
        state = load_data_state(dirname, rank)
        if state is not None:
            out[rank] = state
    return out


def remap_data_state(dirname: str, old_layout: dict,
                     new_num_shards: int, new_shard_index: int):
    """This rank's resharded cursor from a serial committed under a
    DIFFERENT shard layout.

    ``old_layout`` maps each dead-fleet rank to its ``(num_shards,
    shard_index)`` pair (recorded in the serial's meta at save time, or
    re-derived via :func:`~paddle_tpu.data.sharding.shard_layout`).
    tp/fsdp peers — ranks sharing one shard index — read identical data,
    so their blobs must agree byte-for-byte (the ``shard_spec``
    identical-data rule); they collapse to one cursor per stream before
    :func:`~paddle_tpu.data.sharding.merge_cursor_states` re-keys the
    streams onto ``(new_num_shards, new_shard_index)``.

    Returns ``None`` when the serial carries no data states (legacy
    resume); raises ``ValueError`` by name on any inconsistency — a
    wrong guess here silently drops or double-consumes samples, which is
    the exact failure this subsystem exists to kill."""
    from .sharding import merge_cursor_states

    states = load_all_data_states(dirname)
    if not states:
        return None
    shard_counts = set()
    by_shard: dict = {}
    for rank, state in sorted(states.items()):
        pair = old_layout.get(rank, old_layout.get(str(rank)))
        if pair is None:
            raise ValueError(
                f"remap_data_state: serial has a cursor for rank {rank} "
                f"but the recorded shard layout covers only ranks "
                f"{sorted(old_layout)} — cannot tell which stream it "
                f"indexes")
        n, i = int(pair[0]), int(pair[1])
        shard_counts.add(n)
        prev = by_shard.get(i)
        if prev is None:
            by_shard[i] = state
        elif json.dumps(prev, sort_keys=True) != json.dumps(state,
                                                           sort_keys=True):
            raise ValueError(
                f"remap_data_state: ranks sharing shard stream {i} "
                f"committed DIFFERENT cursors — tp/fsdp peers must read "
                f"identical data; the serial is inconsistent")
    if len(shard_counts) != 1:
        raise ValueError(
            f"remap_data_state: recorded layout mixes shard counts "
            f"{sorted(shard_counts)}")
    old_n = shard_counts.pop()
    if sorted(by_shard) != list(range(old_n)):
        # the RECORDED stream count is authoritative: blobs covering only
        # a subset must not silently masquerade as a smaller fleet
        raise ValueError(
            f"remap_data_state: serial records {old_n} shard stream(s) "
            f"but cursors cover only {sorted(by_shard)} — a missing "
            f"stream would silently drop its unconsumed samples")
    return merge_cursor_states(by_shard, new_num_shards, new_shard_index)
