"""Per-host data-shard assignment derived from the named mesh (ISSUE 10).

The PR 7 mesh (``PADDLE_TPU_MESH=dp4,tp2`` → ``parallel.mesh``) fixes how
the GLOBAL batch is laid out over devices: the ``dp`` axis consumes
distinct samples, every other axis (tp/fsdp/pp/…) replicates them.  The
data plane must agree with that layout per HOST: two hosts whose devices
sit in the same dp group must read the SAME samples (their tp shards see
one batch), hosts in different dp groups must read DISJOINT samples, and
the union over all hosts must cover the dataset exactly once per dp
group.  :func:`shard_spec` reduces that to the round-robin
``(num_shards, shard_index)`` pair ``Pipeline.shard`` consumes; hosts are
assumed laid out process-major along the dp axis — the layout
``mesh_from_spec`` builds (device order enumerates later axes fastest).
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["shard_spec", "data_axis_extent"]

#: mesh axes that consume distinct samples (every other axis replicates
#: the batch — tp shards activations, fsdp shards weights, pp stages see
#: the same microbatch stream)
DATA_AXES = ("dp",)


def data_axis_extent(mesh) -> int:
    """The product of data-consuming axis extents of ``mesh`` (a
    ``jax.sharding.Mesh``, a ``"dp4,tp2"`` spec string, or ``None`` for
    the ``PADDLE_TPU_MESH`` env spec).  1 when the mesh has no dp axis —
    a tp/mp-only mesh replicates the whole batch."""
    axes = _axes_of(mesh)
    extent = 1
    for name in DATA_AXES:
        extent *= int(axes.get(name, 1))
    return extent


def _axes_of(mesh) -> dict:
    if mesh is None or isinstance(mesh, str):
        from ..parallel.mesh import env_mesh_spec, parse_mesh_spec

        spec = env_mesh_spec() if mesh is None else mesh
        return parse_mesh_spec(spec) if spec else {}
    # a jax.sharding.Mesh (or anything mesh-shaped): axis name -> extent
    return {name: int(mesh.shape[name]) for name in mesh.axis_names}


def shard_spec(mesh=None, host_rank: Optional[int] = None,
               num_hosts: Optional[int] = None) -> Tuple[int, int]:
    """This host's data shard for ``mesh``: ``(num_shards, shard_index)``.

    ``mesh`` may be a ``jax.sharding.Mesh``, a spec string (``"dp2,tp2"``)
    or ``None`` (the ``PADDLE_TPU_MESH`` env spec; no spec = single-group
    dp, one shard).  ``host_rank`` / ``num_hosts`` default to the
    multihost process index/count.  With dp extent D over H hosts:

     - ``H == 1``      → ``(1, 0)``: one host feeds every dp group (the
       sharded window runner splits the batch locally);
     - ``D % H == 0``  → ``(H, host_rank)``: each host owns D/H dp groups
       and reads a distinct 1/H of the data;
     - ``H % D == 0``  → ``(D, host_rank // (H // D))``: H/D hosts share
       each dp group and read IDENTICAL data (their devices split the
       batch along tp/fsdp, not along samples);
     - anything else is a layout error, raised by name rather than left
       to surface as silent sample overlap.

    Distinct shard indices partition the stream (``Pipeline.shard`` is
    round-robin), so no sample is read twice or lost across the fleet.
    """
    if num_hosts is None or host_rank is None:
        from ..parallel import multihost

        if num_hosts is None:
            num_hosts = multihost.process_count()
        if host_rank is None:
            host_rank = multihost.process_index()
    num_hosts, host_rank = int(num_hosts), int(host_rank)
    if num_hosts < 1 or not 0 <= host_rank < num_hosts:
        raise ValueError(
            f"shard_spec: need 0 <= host_rank < num_hosts, got "
            f"rank={host_rank} of {num_hosts}")
    extent = data_axis_extent(mesh)
    if num_hosts == 1:
        return 1, 0
    if extent % num_hosts == 0:
        return num_hosts, host_rank
    if num_hosts % extent == 0:
        return extent, host_rank // (num_hosts // extent)
    raise ValueError(
        f"shard_spec: dp extent {extent} and host count {num_hosts} do "
        f"not tile (need one to divide the other) — mesh "
        f"{_axes_of(mesh) or 'dp (default)'} cannot be fed by {num_hosts} "
        f"hosts without sample overlap")
