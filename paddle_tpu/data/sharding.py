"""Per-host data-shard assignment derived from the named mesh (ISSUE 10).

The PR 7 mesh (``PADDLE_TPU_MESH=dp4,tp2`` → ``parallel.mesh``) fixes how
the GLOBAL batch is laid out over devices: the ``dp`` axis consumes
distinct samples, every other axis (tp/fsdp/pp/…) replicates them.  The
data plane must agree with that layout per HOST: two hosts whose devices
sit in the same dp group must read the SAME samples (their tp shards see
one batch), hosts in different dp groups must read DISJOINT samples, and
the union over all hosts must cover the dataset exactly once per dp
group.  :func:`shard_spec` reduces that to the round-robin
``(num_shards, shard_index)`` pair ``Pipeline.shard`` consumes; hosts are
assumed laid out process-major along the dp axis — the layout
``mesh_from_spec`` builds (device order enumerates later axes fastest).
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Tuple

__all__ = ["shard_spec", "data_axis_extent", "shard_layout",
           "merge_cursor_states"]

#: mesh axes that consume distinct samples (every other axis replicates
#: the batch — tp shards activations, fsdp shards weights, pp stages see
#: the same microbatch stream)
DATA_AXES = ("dp",)


def data_axis_extent(mesh) -> int:
    """The product of data-consuming axis extents of ``mesh`` (a
    ``jax.sharding.Mesh``, a ``"dp4,tp2"`` spec string, or ``None`` for
    the ``PADDLE_TPU_MESH`` env spec).  1 when the mesh has no dp axis —
    a tp/mp-only mesh replicates the whole batch."""
    axes = _axes_of(mesh)
    extent = 1
    for name in DATA_AXES:
        extent *= int(axes.get(name, 1))
    return extent


def _axes_of(mesh) -> dict:
    from ..parallel.mesh import axes_of

    return axes_of(mesh)


def shard_spec(mesh=None, host_rank: Optional[int] = None,
               num_hosts: Optional[int] = None) -> Tuple[int, int]:
    """This host's data shard for ``mesh``: ``(num_shards, shard_index)``.

    ``mesh`` may be a ``jax.sharding.Mesh``, a spec string (``"dp2,tp2"``)
    or ``None`` (the ``PADDLE_TPU_MESH`` env spec; no spec = single-group
    dp, one shard).  ``host_rank`` / ``num_hosts`` default to the
    multihost process index/count.  With dp extent D over H hosts:

     - ``H == 1``      → ``(1, 0)``: one host feeds every dp group (the
       sharded window runner splits the batch locally);
     - ``D % H == 0``  → ``(H, host_rank)``: each host owns D/H dp groups
       and reads a distinct 1/H of the data;
     - ``H % D == 0``  → ``(D, host_rank // (H // D))``: H/D hosts share
       each dp group and read IDENTICAL data (their devices split the
       batch along tp/fsdp, not along samples);
     - anything else is a layout error, raised by name rather than left
       to surface as silent sample overlap.

    Distinct shard indices partition the stream (``Pipeline.shard`` is
    round-robin), so no sample is read twice or lost across the fleet.
    """
    if num_hosts is None or host_rank is None:
        from ..parallel import multihost

        if num_hosts is None:
            num_hosts = multihost.process_count()
        if host_rank is None:
            host_rank = multihost.process_index()
    num_hosts, host_rank = int(num_hosts), int(host_rank)
    if num_hosts < 1 or not 0 <= host_rank < num_hosts:
        raise ValueError(
            f"shard_spec: need 0 <= host_rank < num_hosts, got "
            f"rank={host_rank} of {num_hosts}")
    extent = data_axis_extent(mesh)
    if num_hosts == 1:
        return 1, 0
    if extent % num_hosts == 0:
        return num_hosts, host_rank
    if num_hosts % extent == 0:
        return extent, host_rank // (num_hosts // extent)
    raise ValueError(
        f"shard_spec: dp extent {extent} and host count {num_hosts} do "
        f"not tile (need one to divide the other) — mesh "
        f"{_axes_of(mesh) or 'dp (default)'} cannot be fed by {num_hosts} "
        f"hosts without sample overlap")


def shard_layout(mesh, num_hosts: int) -> Dict[int, Tuple[int, int]]:
    """Every host's :func:`shard_spec` for one topology: ``rank ->
    (num_shards, shard_index)``.  Recorded into sharded-checkpoint meta at
    save time (``multihost.save_sharded_serial``), so a resharded resume
    can group the per-rank cursor blobs by the shard stream they index
    without re-deriving the dead fleet's layout from env."""
    return {r: shard_spec(mesh, host_rank=r, num_hosts=int(num_hosts))
            for r in range(int(num_hosts))}


# ---------------------------------------------------------------------------
# Cursor remap (ISSUE 14): re-key committed per-rank pipeline cursors from
# one shard layout onto another, with no sample dropped or duplicated.
#
# Why a simple rule exists at all: ``Pipeline.shard(n, i)`` is a
# round-robin partition, and every rank commits its cursor at the SAME
# global step (one _SUCCESS covers the fleet), having consumed the same
# number k of its own shard's samples.  The union of what the fleet
# consumed is then EXACTLY the global-stream prefix [0, k*n) — so the
# remapped cursor for any new layout (m, j) is "shard stream (m, j)
# starting at global position k*n", which is one upstream state (the
# max-position donor's) plus a re-keyed shard filter.  dp4→dp2 merges two
# old streams (they interleave in fixed round-robin order past the cut);
# dp2→dp4 splits them; tp/fsdp peers collapse upstream via the
# ``shard_spec`` identical-data rule (the caller dedupes their blobs).
# ---------------------------------------------------------------------------


def _split_at_shard(state: dict):
    """Walk one pipeline-state tree outermost-stage first and split it at
    the shard node: ``(downstream_wrapper_nodes, shard_node_or_None)``."""
    node = state.get("stage")
    wrappers = []
    while isinstance(node, dict) and node.get("kind") != "shard":
        wrappers.append(node)
        node = node.get("up")
    return wrappers, (node if isinstance(node, dict) else None)


def _consumed_count(shard_index: int, num_shards: int, seen: int) -> int:
    """How many of its own samples shard ``shard_index`` has yielded when
    its upstream cursor sits at ``seen``.  The shard stage only commits
    right after yielding a kept sample (or before any), so ``seen`` is
    either 0 or ``(k-1)*n + i + 1`` — anything else is a torn cursor."""
    if seen == 0:
        return 0
    if (seen - 1) % num_shards != shard_index:
        raise ValueError(
            f"cursor for shard {shard_index}/{num_shards} sits at upstream "
            f"position {seen}, which is not a commit boundary of its own "
            f"stream (expected seen ≡ {shard_index + 1} mod {num_shards}) "
            f"— the blob is torn or from a different layout")
    return (seen - 1 - shard_index) // num_shards + 1


def merge_cursor_states(states_by_shard: Dict[int, dict],
                        new_num_shards: int,
                        new_shard_index: int) -> dict:
    """Re-key one shard stream's worth of committed cursors onto a new
    round-robin layout.

    ``states_by_shard`` maps every OLD shard index (0..n-1, tp/fsdp peers
    already collapsed to one blob each) to its committed ``Pipeline``
    state; the result restores into a pipeline built with
    ``shard(new_num_shards, new_shard_index)`` and the SAME upstream
    stages (source + any global shuffle — seed and buffer size included),
    positioned so the fleet's new shard streams cover exactly the samples
    the old fleet had not consumed.  Deterministic and pure: same blobs
    in, same cursor out, on every new rank.

    Raises ``ValueError`` (by name, never silently) when the layouts do
    not tile, a shard stream's blob is missing, the streams are not
    aligned at one global commit point, or the pipeline shuffles BELOW
    the shard stage (a per-shard shuffle permutes each rank's stream
    independently — there is no mesh-independent global order to cut)."""
    new_num_shards = int(new_num_shards)
    new_shard_index = int(new_shard_index)
    if new_num_shards < 1 or not 0 <= new_shard_index < new_num_shards:
        raise ValueError(
            f"merge_cursor_states: need 0 <= new_shard_index < "
            f"new_num_shards, got {new_shard_index} of {new_num_shards}")
    old_n = len(states_by_shard)
    if sorted(states_by_shard) != list(range(old_n)):
        raise ValueError(
            f"merge_cursor_states: need one cursor per old shard stream "
            f"0..{old_n - 1}, got indices {sorted(states_by_shard)} — a "
            f"missing stream would silently drop its unconsumed samples")
    if old_n == new_num_shards:
        # layout-preserving rank permutation: the stream itself transfers
        return copy.deepcopy(states_by_shard[new_shard_index])
    if old_n % new_num_shards != 0 and new_num_shards % old_n != 0:
        raise ValueError(
            f"merge_cursor_states: old shard count {old_n} and new shard "
            f"count {new_num_shards} do not tile (need one to divide the "
            f"other) — round-robin streams cannot be re-keyed without "
            f"sample overlap")

    split = {}
    epochs = set()
    wrapper_kinds = set()
    for i, st in states_by_shard.items():
        if not isinstance(st, dict) or "stage" not in st:
            raise ValueError(
                f"merge_cursor_states: shard {i}'s blob is not a pipeline "
                f"state (no 'stage' tree)")
        wrappers, shard_node = _split_at_shard(st)
        if shard_node is None:
            raise ValueError(
                f"merge_cursor_states: shard {i}'s cursor has no shard "
                f"stage — a layout change cannot be applied to an "
                f"unsharded pipeline state")
        for w in wrappers:
            if w.get("kind") == "shuffle":
                raise ValueError(
                    "merge_cursor_states: pipeline shuffles BELOW the "
                    "shard stage (shard(...).shuffle(...)), so each "
                    "rank's order is private to the old layout and "
                    "cannot be merged; build elastic pipelines as "
                    "from_reader(...).shuffle(...).shard_by_mesh(...) — "
                    "one global order, any mesh")
        split[i] = (wrappers, shard_node)
        epochs.add((int(st.get("epoch", 0)),
                    bool(st.get("epoch_done", False))))
        wrapper_kinds.add(tuple(w.get("kind") for w in wrappers))
    if len(epochs) > 1:
        raise ValueError(
            f"merge_cursor_states: shard cursors disagree on the epoch "
            f"{sorted(epochs)} — not one atomic fleet commit")
    if len(wrapper_kinds) > 1:
        raise ValueError(
            f"merge_cursor_states: shard cursors come from differently "
            f"shaped pipelines {sorted(wrapper_kinds)}")

    ks = {i: _consumed_count(i, old_n, int(sh.get("seen", 0)))
          for i, (_, sh) in split.items()}
    if len(set(ks.values())) != 1:
        raise ValueError(
            f"merge_cursor_states: shard streams are not aligned at one "
            f"global commit point (per-shard consumed counts {ks}) — the "
            f"blobs span different steps, or a short final batch was "
            f"committed unevenly")
    cut = ks[0] * old_n  # the fleet consumed exactly global prefix [0, cut)
    # the donor is the old stream whose upstream cursor sits exactly AT
    # the cut: with k samples consumed each, that is shard old_n-1 (its
    # k-th kept sample is global ordinal cut-1); every other stream's
    # upstream stopped short of the cut by < old_n skipped-not-mine
    # samples, all already consumed by later shards
    out = copy.deepcopy(states_by_shard[old_n - 1])
    _, shard_node = _split_at_shard(out)
    shard_node["seen"] = cut
    return out
