"""Checkpointable device prefetch: staged-but-uncommitted is REPLAYED.

:class:`~paddle_tpu.fluid.prefetch.DevicePrefetcher` runs ahead of the
training loop by design — when a window is dispatched, the staging thread
has already pulled (and possibly device_put) one or more FUTURE windows
from the pipeline.  Snapshotting ``pipeline.state()`` from the consumer
at checkpoint time would therefore record the PREFETCH HEAD, and a resume
would silently skip every staged-but-never-trained sample.

:class:`CheckpointablePrefetcher` fixes the attribution: on the staging
thread, immediately after window ``k``'s batches are pulled (and before
window ``k+1``'s first pull — the stage callback runs between the two),
it snapshots the pipeline state, which at that instant points at window
``k+1``'s first sample.  The snapshots ride a FIFO next to the staged
windows (the ``_stage_spans`` pattern), and as the consumer takes window
``k`` it pops the matching snapshot into ``last_state``.  A checkpoint
committed after training window ``k`` therefore records "resume at
window ``k+1``'s first sample": windows still sitting in the prefetch
queue are re-staged from the pipeline on restore — replayed, never lost.

The consumer side also accounts every window's input-wait through
``data.note_data_wait`` (the ``data.wait_ms`` counter, the
``train.data_wait_s`` SLO watchdog feed, and ``data.stall`` run events),
so an injected ``PADDLE_FAULT_DATA_STALL_MS`` stall breaches the SLO the
same way a slow dispatch does.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Iterable, Optional

from ..fluid.prefetch import DevicePrefetcher
from .pipeline import CheckpointableIterator, note_data_wait

__all__ = ["CheckpointablePrefetcher"]


class CheckpointablePrefetcher(DevicePrefetcher):
    """A :class:`DevicePrefetcher` over a checkpointable pipeline.

    ``source`` is the per-step feed iterable (usually ``feeder.feed(b)
    for b in pipeline()``) and ``pipeline`` the
    :class:`~paddle_tpu.data.pipeline.CheckpointableIterator` that
    ultimately produces it — the two must be the same stream: every
    ``source`` item must pull exactly one pipeline batch, lazily, on the
    pulling thread (a generator expression does; a pre-built list does
    not).  ``last_state`` always holds the state blob to commit for the
    windows consumed SO FAR."""

    def __init__(self, source: Iterable[Dict[str, object]],
                 pipeline: CheckpointableIterator, n_steps: int = 1,
                 place=None, depth: Optional[int] = None, stage_fn=None):
        super().__init__(source, n_steps=n_steps, place=place, depth=depth,
                         stage_fn=stage_fn)
        self._pipeline = pipeline
        self._win_states: deque = deque()
        #: resume point covering everything consumed so far; before any
        #: window is taken this is the pipeline's current (start) state
        self.last_state: dict = pipeline.state()

    def _stage(self, batches):
        item = super()._stage(batches)
        # runs on the staging thread BETWEEN window pulls: the pipeline
        # cursor now points at the first sample after this window — the
        # exact resume point once this window commits
        self._win_states.append(self._pipeline.state())
        return item

    def __iter__(self):
        it = super().__iter__()
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            wait_s = time.perf_counter() - t0
            if self._win_states:
                self.last_state = self._win_states.popleft()
            note_data_wait(wait_s, count=item[1])
            yield item
