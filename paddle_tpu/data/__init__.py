"""paddle_tpu.data — the checkpointable streaming data plane (ISSUE 10).

A sharded, prefetching input pipeline whose ITERATOR POSITION is a
checkpoint artifact: state blobs commit atomically with model state under
the serial-dir ``_SUCCESS`` protocol (one per host rank), and a resumed
run consumes the byte-identical sample sequence an uninterrupted run
would have, starting at the first un-committed sample.  Operate guide:
docs/DATA.md; the resume semantics are part of docs/ROBUSTNESS.md.

    from paddle_tpu import data

    pipe = (data.from_reader(sample_reader)
                .shard_by_mesh()          # per-host slice of PADDLE_TPU_MESH
                .shuffle(512, seed=7)     # resumable, keyed on (seed, epoch)
                .batch(64))
    trainer.train(..., reader=pipe)       # Trainer commits/restores state

Pieces: :mod:`pipeline` (the CheckpointableIterator protocol + stages),
:mod:`sharding` (mesh → per-host shard assignment), :mod:`prefetch`
(window staging whose uncommitted lookahead is replayed, never lost),
:mod:`checkpoint` (the per-rank ``data_state`` blob under ``_SUCCESS``).
"""

from .checkpoint import (DATA_STATE_PREFIX, data_state_path,
                         load_data_state, save_data_state)
from .pipeline import (CheckpointableIterator, Pipeline, from_reader,
                       is_checkpointable, note_data_wait, timed)
from .prefetch import CheckpointablePrefetcher
from .sharding import data_axis_extent, shard_spec

__all__ = [
    "CheckpointableIterator", "Pipeline", "from_reader",
    "is_checkpointable", "note_data_wait", "timed",
    "CheckpointablePrefetcher", "shard_spec", "data_axis_extent",
    "DATA_STATE_PREFIX", "data_state_path", "save_data_state",
    "load_data_state",
]
