"""Checkpointable streaming input pipeline (ROADMAP item 5).

The reader decorators (``paddle_tpu.reader.decorator``) are stateless
generator factories: kill a run mid-epoch and the only resume options are
"replay the epoch from sample 0" or "skip an unknowable number of
samples" — the one part of the stack the elastic supervisor (PR 1) and
guardian (PR 3) cannot make deterministic.  This module makes iterator
position a first-class checkpoint artifact: every stage implements the
:class:`CheckpointableIterator` protocol (``state()`` / ``restore()``),
state blobs are plain JSON-serializable dicts small enough to commit with
every model checkpoint (one per host rank, under the same ``_SUCCESS``
barrier — see ``paddle_tpu.data.checkpoint``), and a restored pipeline
yields the byte-identical sample sequence an uninterrupted run would
have, starting at the first un-committed sample.

Stages (built fluently from :func:`from_reader`)::

    pipe = (data.from_reader(sample_reader)        # legacy reader adapter
                .shard(num_hosts, host_rank)       # or .shard_by_mesh()
                .shuffle(buf_size=512, seed=7)     # resumable, per-epoch
                .batch(64))                        # -> DataFeeder batches

 - ``shard(n, i)`` keeps every n-th sample (round-robin partition: no
   overlap, no loss across shards); ``shard_by_mesh`` derives ``(n, i)``
   from the PR 7 named mesh (``data.sharding.shard_spec``): hosts in the
   same dp group read identical data, distinct dp groups partition it.
 - ``shuffle`` draws each buffer's permutation from a private
   ``random.Random`` keyed on ``(seed, epoch, buffer_index)`` — epoch N
   buffer k is reproducible *directly*, with no replay of prior epochs or
   buffers, which is what makes the cursor resumable mid-buffer.
 - ``batch`` groups samples into ``DataFeeder``-shaped lists and feeds
   the ``data.samples`` / ``data.bytes`` observe counters.

Epoch contract: a :class:`Pipeline` is callable like a legacy reader —
each call after a completed epoch advances to the next epoch (stages see
``set_epoch``); ``state()`` carries the epoch, so a restored pipeline
resumes mid-epoch N without consuming epochs 0..N-1.

Determinism notes: ``random.Random`` seeded with a string hashes it with
sha512 (not the randomized ``hash()``), so permutations reproduce across
processes; an unseeded ``shuffle`` is NOT checkpointable and ``state()``
says so loudly.  State snapshots are only consistent from the thread
driving the iterator — the prefetch wrapper
(:class:`paddle_tpu.data.prefetch.CheckpointablePrefetcher`) snapshots on
its staging thread at window boundaries for exactly this reason.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional

__all__ = [
    "CheckpointableIterator", "Pipeline", "from_reader",
    "is_checkpointable", "note_data_wait", "timed",
]


class CheckpointableIterator:
    """The resumable-iterator protocol every pipeline stage implements.

    ``state()`` returns a JSON-serializable dict identifying the position
    of the FIRST SAMPLE NOT YET YIELDED; ``restore(state)`` repositions a
    freshly built, identically shaped pipeline there; ``set_epoch(e)``
    rewinds to the start of epoch ``e`` (stages that randomize re-key
    their RNG on it).  Iteration covers ONE epoch: ``__next__`` raises
    ``StopIteration`` at epoch end and the driver decides whether another
    epoch starts (``Pipeline.__call__``)."""

    def state(self) -> dict:
        raise NotImplementedError

    def restore(self, state: dict) -> None:
        raise NotImplementedError

    def set_epoch(self, epoch: int) -> None:
        raise NotImplementedError

    def __iter__(self):
        return self

    def __next__(self):
        raise NotImplementedError


def is_checkpointable(reader) -> bool:
    """True when ``reader`` speaks the state()/restore() protocol (the
    Trainer uses this to pick exact-resume over sample-skip replay)."""
    return isinstance(reader, CheckpointableIterator)


def _stage_rng(seed, epoch: int, index: int) -> random.Random:
    """Private RNG keyed on (seed, epoch, index).  String seeding goes
    through sha512 — deterministic across processes, unlike ``hash()`` —
    so a resumed subprocess reproduces the exact permutation."""
    return random.Random(f"{seed}|{epoch}|{index}")


class _ReaderSource(CheckpointableIterator):
    """Legacy-reader adapter: wraps a paddle-style reader factory (a
    callable returning a fresh per-epoch generator) with a sample-count
    cursor.  Restore re-instantiates the generator and skips ``cursor``
    samples — O(cursor) replay, the only generic contract an opaque
    generator admits; sources that can seek should implement the protocol
    directly."""

    def __init__(self, reader_fn: Callable[[], Iterator]):
        if not callable(reader_fn):
            raise TypeError(
                "from_reader wants a reader FACTORY (callable returning a "
                f"generator), got {type(reader_fn).__name__}")
        self._fn = reader_fn
        self.epoch = 0
        self.cursor = 0
        self._gen: Optional[Iterator] = None
        self._pending_skip = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.cursor = 0
        self._gen = None
        self._pending_skip = 0

    def state(self) -> dict:
        return {"kind": "reader", "epoch": self.epoch, "cursor": self.cursor}

    def restore(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.cursor = int(state["cursor"])
        self._gen = None
        self._pending_skip = self.cursor

    def __next__(self):
        from ..fluid import fault as _fault

        if self._gen is None:
            self._gen = iter(self._fn())
            for _ in range(self._pending_skip):
                next(self._gen)
            self._pending_skip = 0
        sample = next(self._gen)  # StopIteration = epoch end
        _fault.data_stall(self.cursor)  # deterministic slow-input oracle
        self.cursor += 1
        return sample


class _ShardStage(CheckpointableIterator):
    """Round-robin shard filter: keeps upstream samples whose ordinal
    satisfies ``i % num_shards == shard_index``.  Shards with distinct
    indices PARTITION the upstream stream (no overlap, no loss), which is
    the property the mesh test asserts for dp4 and dp2x tp2."""

    def __init__(self, up: CheckpointableIterator, num_shards: int,
                 shard_index: int):
        num_shards, shard_index = int(num_shards), int(shard_index)
        if num_shards < 1 or not 0 <= shard_index < num_shards:
            raise ValueError(
                f"shard: need 0 <= shard_index < num_shards, got "
                f"index={shard_index} of {num_shards}")
        self._up = up
        self.num_shards = num_shards
        self.shard_index = shard_index
        self._seen = 0

    def set_epoch(self, epoch: int) -> None:
        self._up.set_epoch(epoch)
        self._seen = 0

    def state(self) -> dict:
        return {"kind": "shard", "seen": self._seen,
                "up": self._up.state()}

    def restore(self, state: dict) -> None:
        self._up.restore(state["up"])
        self._seen = int(state["seen"])

    def __next__(self):
        while True:
            sample = next(self._up)
            i = self._seen
            self._seen += 1
            if i % self.num_shards == self.shard_index:
                return sample


class _ShuffleStage(CheckpointableIterator):
    """Buffered shuffle whose cursor is resumable MID-BUFFER.

    Buffer ``k`` of epoch ``e`` is permuted by a private RNG keyed on
    ``(seed, e, k)``: reproducing any buffer needs neither the previous
    buffers nor previous epochs, so ``state()`` is just (upstream position
    at buffer start, buffer index, offset into the permuted buffer) and
    ``restore`` refills one buffer and skips to the offset."""

    def __init__(self, up: CheckpointableIterator, buf_size: int, seed=None):
        self._up = up
        self.buf_size = max(1, int(buf_size))
        self.seed = seed
        self.epoch = 0
        self._buf: Optional[list] = None
        self._off = 0
        self._buf_index = 0
        self._buf_start: Optional[dict] = None
        self._pending_off = 0

    def set_epoch(self, epoch: int) -> None:
        self._up.set_epoch(epoch)
        self.epoch = int(epoch)
        self._buf = None
        self._off = 0
        self._buf_index = 0
        self._buf_start = None
        self._pending_off = 0

    def state(self) -> dict:
        if self.seed is None:
            raise ValueError(
                "shuffle(seed=None) is not checkpointable: an unseeded "
                "permutation cannot be reproduced on restore — pass a seed")
        if self._buf is None or self._off >= len(self._buf):
            # buffer boundary: the next sample starts a fresh buffer at
            # the upstream's CURRENT position
            nxt = self._buf_index + (0 if self._buf is None else 1)
            return {"kind": "shuffle", "epoch": self.epoch,
                    "buf_index": nxt, "off": 0, "up": self._up.state()}
        return {"kind": "shuffle", "epoch": self.epoch,
                "buf_index": self._buf_index, "off": self._off,
                "up": self._buf_start}

    def restore(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self._up.restore(state["up"])
        self._buf_index = int(state["buf_index"])
        self._buf = None
        self._off = 0
        self._buf_start = None
        self._pending_off = int(state["off"])

    def _refill(self) -> None:
        self._buf_start = self._up.state() if self.seed is not None else None
        buf = []
        try:
            while len(buf) < self.buf_size:
                buf.append(next(self._up))
        except StopIteration:
            pass
        if not buf:
            raise StopIteration
        rng = (random.Random() if self.seed is None
               else _stage_rng(self.seed, self.epoch, self._buf_index))
        rng.shuffle(buf)
        self._buf = buf
        self._off = min(self._pending_off, len(buf))
        self._pending_off = 0

    def __next__(self):
        if self._buf is not None and self._off >= len(self._buf):
            self._buf_index += 1
            self._buf = None
        if self._buf is None:
            self._refill()
        sample = self._buf[self._off]
        self._off += 1
        return sample


def _nbytes(obj) -> int:
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, (tuple, list)):
        return sum(_nbytes(x) for x in obj)
    if isinstance(obj, dict):
        return sum(_nbytes(x) for x in obj.values())
    return 8  # scalars / opaque python objects: a nominal word


class _BatchStage(CheckpointableIterator):
    """Group samples into ``DataFeeder``-shaped lists (the same surface
    as ``paddle.batch``).  State is the upstream position at the batch
    boundary — batches are the pipeline's atomic commit unit, so a
    checkpoint taken between batches resumes at the next batch's first
    sample with nothing split."""

    def __init__(self, up: CheckpointableIterator, batch_size: int,
                 drop_last: bool = False):
        if int(batch_size) < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._up = up
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def set_epoch(self, epoch: int) -> None:
        self._up.set_epoch(epoch)

    def state(self) -> dict:
        return {"kind": "batch", "up": self._up.state()}

    def restore(self, state: dict) -> None:
        self._up.restore(state["up"])

    def __next__(self):
        from ..observe import trace as _trace

        with _trace.span("data.stage", batch_size=self.batch_size):
            buf = []
            try:
                while len(buf) < self.batch_size:
                    buf.append(next(self._up))
            except StopIteration:
                if not buf or self.drop_last:
                    raise StopIteration from None
        try:
            from .. import observe

            reg = observe.registry()
            reg.inc("data.samples", len(buf))
            reg.inc("data.bytes", _nbytes(buf))
        except Exception:
            pass  # metrics must never take the input pipeline down
        return buf


class _MapStage(CheckpointableIterator):
    """Apply ``fn`` to every upstream item.  Stateless by construction —
    ``fn`` must be deterministic for resume to stay byte-identical; side
    effects re-fire on replayed (staged-but-uncommitted) items, which is
    exactly what the kill-and-resume oracle's recording map relies on."""

    def __init__(self, up: CheckpointableIterator, fn: Callable):
        self._up = up
        self._fn = fn

    def set_epoch(self, epoch: int) -> None:
        self._up.set_epoch(epoch)

    def state(self) -> dict:
        return {"kind": "map", "up": self._up.state()}

    def restore(self, state: dict) -> None:
        self._up.restore(state["up"])

    def __next__(self):
        return self._fn(next(self._up))


class Pipeline(CheckpointableIterator):
    """The user-facing handle over a stage chain: fluent builders, the
    legacy callable-reader surface, and whole-pipeline state.

    ``pipe()`` returns the epoch's iterator exactly like a decorated
    reader — but statefully: after an epoch completes, the next call
    advances every stage to the next epoch (shuffle re-keys its RNG), and
    after ``restore`` the next call resumes mid-epoch instead."""

    def __init__(self, stage: CheckpointableIterator, epoch: int = 0):
        self._stage = stage
        self.epoch = int(epoch)
        self._epoch_done = False

    # -- builders ----------------------------------------------------------
    def shard(self, num_shards: int, shard_index: int) -> "Pipeline":
        return Pipeline(_ShardStage(self._stage, num_shards, shard_index),
                        self.epoch)

    def shard_by_mesh(self, mesh=None, host_rank: Optional[int] = None,
                      num_hosts: Optional[int] = None) -> "Pipeline":
        """Shard for this host's slice of the named mesh (docs/SPMD.md):
        ``data.sharding.shard_spec`` maps (mesh, host) to a round-robin
        ``(num_shards, shard_index)`` — tp/fsdp replicas read identical
        data, distinct dp groups partition it."""
        from .sharding import shard_spec

        n, i = shard_spec(mesh, host_rank=host_rank, num_hosts=num_hosts)
        return self.shard(n, i)

    def shuffle(self, buf_size: int, seed=None) -> "Pipeline":
        return Pipeline(_ShuffleStage(self._stage, buf_size, seed),
                        self.epoch)

    def batch(self, batch_size: int, drop_last: bool = False) -> "Pipeline":
        return Pipeline(_BatchStage(self._stage, batch_size, drop_last),
                        self.epoch)

    def map(self, fn: Callable) -> "Pipeline":
        return Pipeline(_MapStage(self._stage, fn), self.epoch)

    # -- protocol ----------------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self._epoch_done = False
        self._stage.set_epoch(self.epoch)

    def state(self) -> dict:
        return {"version": 1, "epoch": self.epoch,
                "epoch_done": self._epoch_done, "stage": self._stage.state()}

    def restore(self, state: dict) -> None:
        if int(state.get("version", -1)) != 1:
            raise ValueError(
                f"data_state version {state.get('version')!r} is not "
                f"readable by this pipeline (expected 1)")
        self.epoch = int(state["epoch"])
        self._epoch_done = bool(state.get("epoch_done", False))
        # set_epoch first: it zeroes every stage's counters, then the
        # stage-state restore repositions them (a restore into a pipeline
        # mid-iteration must not inherit stale cursors)
        self._stage.set_epoch(self.epoch)
        self._stage.restore(state["stage"])

    def __next__(self):
        try:
            return next(self._stage)
        except StopIteration:
            self._epoch_done = True
            raise

    def __call__(self):
        """Legacy reader surface (``for batch in pipe():``): a call after
        a completed epoch starts the next one; a call after ``restore``
        (or the first call) continues from the current cursor."""
        if self._epoch_done:
            self.set_epoch(self.epoch + 1)
        return iter(self)


def from_reader(reader_fn: Callable[[], Iterator]) -> Pipeline:
    """Wrap a legacy paddle-style reader factory as a checkpointable
    pipeline source (sample-count cursor; see :class:`_ReaderSource`)."""
    return Pipeline(_ReaderSource(reader_fn))


# ---------------------------------------------------------------------------
# data-wait accounting (shared by the prefetch wrapper and the trainer's
# per-step loop): counters + SLO watchdog + stall events
# ---------------------------------------------------------------------------


def note_data_wait(wait_s: float, **ctx) -> None:
    """Record one input-wait interval: the ``data.wait_ms`` counter, the
    ``train.data_wait_s`` SLO watchdog feed (an injected input stall
    breaches the same way a slow step does — docs/OBSERVABILITY.md §8),
    and a ``data.stall`` run event when the wait exceeds
    ``PADDLE_DATA_STALL_EVENT_MS``."""
    try:
        from .. import observe
        from ..fluid import envcontract
        from ..observe import goodput, watchdog

        wait_s = float(wait_s)
        observe.registry().inc("data.wait_ms", wait_s * 1000.0)
        watchdog.observe_value("train.data_wait_s", wait_s, **ctx)
        # input-starved wall-clock is data_wait-state time in the goodput
        # ledger (the fraction an autoscaler reads drops when the pipeline
        # cannot keep the device fed)
        goodput.note("data_wait", wait_s)
        if wait_s * 1000.0 > float(envcontract.get(
                "PADDLE_DATA_STALL_EVENT_MS")):
            observe.emit("data.stall", wait_ms=round(wait_s * 1000.0, 3),
                         **ctx)
    except Exception:
        pass  # observability must never take the input pipeline down


def timed(iterator, **ctx):
    """Yield from ``iterator``, feeding every item's pull time through
    :func:`note_data_wait` — the per-step training loop's input-stall
    instrumentation (the windowed loop gets the same accounting from
    :class:`~paddle_tpu.data.prefetch.CheckpointablePrefetcher`)."""
    it = iter(iterator)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        note_data_wait(time.perf_counter() - t0, **ctx)
        yield item
