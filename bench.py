"""Benchmark driver: prints the headline metrics as JSON lines.

With no args it measures BOTH driver metrics (BASELINE.json): ResNet-50
training images/sec/chip and Transformer-base tokens/sec/chip, printing one
JSON line per model and a final combined line carrying both numbers (the
driver records the output; the combined last line guarantees both metrics
land in BENCH_r{N}.json however many lines are parsed).  Set
BENCH_MODEL=resnet|transformer|mnist to measure a single model.

vs_baseline compares against the reference's best published number for the
model (reference benchmark/IntelOptimizedPaddle.md:43-45 — ResNet-50
training 84.08 images/sec on 2x Xeon 6148 MKL-DNN bs=256; the reference
publishes no per-chip TPU or Transformer figure, so the Transformer baseline
is the same hardware-era proxy documented in BASELINE.md).

Mixed precision: on an accelerator the bench trains with bf16 AMP
(fluid.amp — matmuls/convs in bfloat16 with fp32 accumulation and fp32
master weights), the TPU equivalent of the reference's float16 transpiler
(ref: paddle/contrib/float16/float16_transpiler.py).  BENCH_AMP=0 disables.

Transport ceiling note (measured 2026-07-30): through this tunneled TPU,
even a single chained bf16 4096^3 matmul achieves only ~18 TFLOPs (per-
dispatch latency ~7ms dominates); the ResNet-50 train step at ~21.5
achieved TFLOPs already exceeds the single-op dispatch ceiling, i.e. the
reported ~11% MFU is bounded by the tunnel transport, not by the compiled
program.  On directly-attached TPU hardware the same XLA program has no
such per-step floor.

Hardening (round-1/-3 postmortems): the TPU backend behind the `axon` tunnel
can HANG on first use, not just error — and can stay wedged for many minutes
before recovering.  The platform is probed in a subprocess with a timeout
and, on a hang, RETRIED with long pauses until BENCH_PROBE_BUDGET (default
25 min) is spent; only a clean 'cpu' answer or an exhausted budget concedes
CPU (via jax.config.update — env vars are too late: sitecustomize
pre-imports jax).  Every failure path still emits JSON diagnostic lines, and
a CPU concession records whether it was 'no_tpu' or
'wedged_budget_exhausted'.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Reference numbers to compare against (see module docstring).
BASELINES = {
    "resnet": 84.08,        # images/sec, ResNet-50 train bs=256, 2x Xeon 6148
    "transformer": 15468.3,  # tokens/sec, derived dimensionally from the
                             # reference's largest published seq-model figure:
                             # LSTM 2-layer h=1280, bs=256, padded seq len 100
                             # (reference benchmark/README.md:105,131-136) at
                             # 1655 ms/batch -> 256*100/1.655 = 15468 tok/s.
                             # The reference has no Transformer number; this is
                             # the honest tokens/sec of its best seq2seq-scale
                             # benchmark, not a ms/batch figure reused as a rate.
    "mnist": 10000.0,       # images/sec, no published figure; nominal.
    "resnet_infer": 217.69,  # images/sec, ResNet-50 infer bs=16
                             # (IntelOptimizedPaddle.md:85-87)
    "vgg": 28.46,            # images/sec, VGG-19 train bs=64, 2x Xeon 6148
                             # (IntelOptimizedPaddle.md:33-35)
    "alexnet": 399.00,       # images/sec, AlexNet train bs=64
                             # (IntelOptimizedPaddle.md:63-65)
    "googlenet": 250.46,     # images/sec, GoogleNet train bs=64
                             # (IntelOptimizedPaddle.md:53-55)
    "rnn": 347.83,           # sequences/sec: LSTM 2-layer+fc h=512 bs=64
                             # at 184 ms/batch (reference
                             # benchmark/README.md:113-120) -> 64/0.184
}

# Peak dense bf16 TFLOPs per chip by TPU generation, for MFU reporting.
# Matched as substrings of jax.devices()[0].device_kind (lowercased).
PEAK_BF16_TFLOPS = [
    ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0), ("v5 lite", 197.0),
    ("v5litepod", 197.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def peak_tflops(device_kind: str) -> float:
    dk = (device_kind or "").lower()
    for key, val in PEAK_BF16_TFLOPS:
        if key in dk:
            return val
    return 197.0  # unknown generation: assume v5e-class

PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256), jnp.bfloat16);"
    "v = (x @ x).sum();"
    "print('PROBE_OK', jax.devices()[0].platform, float(v))"
)


def _probe_diag(rec: dict) -> None:
    """Probe retry/wedge diagnostics go to STDERR: stdout is the metric
    channel and every line of it must parse as a clean BENCH JSON line
    (the round-5 BENCH tail was polluted by these — ISSUE 9 satellite;
    regression: tests/test_bench_output.py parses every stdout line)."""
    print(json.dumps(rec), file=sys.stderr, flush=True)


def _probe_once(timeout: float) -> str:
    """Run a tiny jitted matmul in a subprocess; one attempt.

    Returns the platform string on success, 'cpu' if the backend is
    genuinely CPU, 'wedged' if the subprocess HUNG (the axon tunnel wedges
    rather than erroring, so an in-process try/except cannot catch it), or
    'crashed' if it completed without a PROBE_OK (deterministic init
    failure — a dead tunnel process / broken libtpu errors fast and
    retrying for the full budget would just stall the bench).
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=timeout)
        for line in out.stdout.splitlines():
            if line.startswith("PROBE_OK"):
                return line.split()[1]
    except (subprocess.TimeoutExpired, OSError):
        return "wedged"
    return "crashed"


def probe_platform(timeout: float = 180.0) -> tuple:
    """Probe the default backend, retrying through tunnel wedges.

    The axon TPU tunnel is known to wedge completely after heavy use and
    recover after minutes (docs/PERF.md); a single timed-out probe is
    therefore NOT evidence that there is no TPU.  Policy:

    - probe in a subprocess with `timeout` per attempt;
    - a clean 'PROBE_OK cpu' means there is genuinely no accelerator:
      concede CPU immediately (no retry);
    - a hang/crash means 'wedged': retry with a long pause
      (BENCH_PROBE_PAUSE, default 120 s) until a total budget
      (BENCH_PROBE_BUDGET, default 1500 s = 25 min) is exhausted.

    Emits one JSON diagnostic line per failed attempt so the log
    distinguishes "wedged, retrying" from "no TPU".  Returns
    (platform, probe_status) where probe_status is 'ok', 'no_tpu', or
    'wedged_budget_exhausted'.
    """
    budget = float(os.environ.get("BENCH_PROBE_BUDGET", "1500"))
    pause = float(os.environ.get("BENCH_PROBE_PAUSE", "120"))
    t_start = time.monotonic()
    attempt = 0
    crashes = 0
    while True:
        attempt += 1
        plat = _probe_once(timeout)
        elapsed = time.monotonic() - t_start
        if plat == "cpu":
            return "cpu", "no_tpu"
        if plat not in ("wedged", "crashed"):
            return plat, "ok"
        if plat == "crashed":
            # deterministic failures don't heal with waiting: allow ONE
            # quick retry (transient flake), then concede
            crashes += 1
            if crashes >= 2:
                _probe_diag({
                    "event": "tpu_probe_crashed", "attempts": attempt,
                    "elapsed_sec": round(elapsed, 1),
                    "note": "backend init fails fast (not a hang); "
                            "falling back to CPU"})
                return "cpu", "probe_crashed"
        remaining = budget - (time.monotonic() - t_start)
        if remaining <= pause:
            _probe_diag({
                "event": "tpu_probe_gave_up", "attempts": attempt,
                "elapsed_sec": round(elapsed, 1),
                "note": "accelerator wedged for the whole probe budget; "
                        "falling back to CPU"})
            return "cpu", "wedged_budget_exhausted"
        _probe_diag({
            "event": "tpu_probe_wedged_retrying", "attempt": attempt,
            "elapsed_sec": round(elapsed, 1),
            "retry_in_sec": pause,
            "budget_remaining_sec": round(remaining, 1)})
        time.sleep(pause)


def _cache_counters():
    """(hit, miss) snapshot of the persistent compile cache's counters."""
    from paddle_tpu.fluid import profiler as _prof

    c = _prof.counters()
    return (c.get("compile_cache.hit", 0), c.get("compile_cache.miss", 0))


def _cold_info(t_compile, before, after, window_steps=1, prefetch=0):
    """BENCH-line cold-start fields: the first dispatch's wall time
    (trace + XLA compile + step) reported SEPARATELY from steady-state
    throughput, plus whether it was served warm from the persistent
    compile cache (PADDLE_COMPILE_CACHE_DIR) — so warm-vs-cold runs are
    distinguishable in the trajectory.  Every line also records the
    dispatch shape of the measured loop: ``window_steps`` (steps fused
    per run_steps dispatch; 1 = per-step), the resulting
    ``dispatches_per_step`` amortization, and the ``prefetch`` depth the
    loop staged input with (0 = synchronous / fixed resident feed)."""
    h0, m0 = before
    h1, m1 = after
    return {"compile_seconds": round(t_compile, 3),
            "cache_hit": bool(h1 > h0 and m1 == m0),
            "window_steps": int(window_steps),
            "dispatches_per_step": round(1.0 / max(1, int(window_steps)), 4),
            "prefetch": int(prefetch)}


def _timed_run_mesh(fluid, loss, feed, steps, spd, mesh_spec):
    """BENCH_MESH=dp4,tp2 (or PADDLE_TPU_MESH): the whole-program SPMD
    path — one ParallelExecutor over the named mesh, ``spd`` steps fused
    per dispatch (BENCH_SPD, default 4), so every BENCH line on this path
    records ``dispatches_per_step < 1`` plus the mesh label.  The batch
    must divide the mesh's dp extent (the runner raises the named
    ValueError otherwise — size your BENCH_*_BS accordingly)."""
    from paddle_tpu.fluid.parallel_executor import ParallelExecutor

    spd = spd if spd > 1 else min(4, max(1, steps))
    n_chunks = max(1, steps // spd)
    steps = n_chunks * spd
    prog = fluid.default_main_program()
    pe = ParallelExecutor(loss_name=loss.name, main_program=prog,
                          mesh=mesh_spec)
    feed_w = {k: np.stack([np.asarray(v)] * spd) for k, v in feed.items()}
    cc0 = _cache_counters()
    t_c = time.perf_counter()
    pe.run_steps([loss], feed=feed_w, n_steps=spd, feed_per_step=True)
    cold = _cold_info(time.perf_counter() - t_c, cc0, _cache_counters(),
                      spd, 0)
    cold["mesh"] = pe.mesh_label
    t0 = time.perf_counter()
    out = None
    for _ in range(n_chunks):
        (out,) = pe.run_steps([loss], feed=feed_w, n_steps=spd,
                              feed_per_step=True)
    last = float(np.asarray(out).reshape(-1)[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(last), f"non-finite loss {last}"
    return dt, steps, pe, cold


def timed_run(fluid, on_accel, loss, feed, steps, warmup=2):
    """Shared harness: startup program, warmup (compile), timed steps.

    The feed is staged onto the device ONCE before timing (Executor accepts
    device-resident jax arrays and passes them through) — the equivalent of
    the reference's `--use_reader_op` path where data is already resident
    rather than re-fed from numpy every step (ref:
    benchmark/fluid/fluid_benchmark.py:149).

    BENCH_SPD=K>1 (or the library-wide PADDLE_TPU_SPD, honored when the
    bench knob is unset) opts into Executor.run_steps (lax.scan, K steps
    per dispatch) — guardian-gated and dynamic-fp16-loss-scaled programs
    included, since ISSUE 6 folded the sentinel + scaler into the scan
    carry.  Measured 2026-07-30 over the tunneled TPU: NOT the default
    because the executor's per-step async dispatches already pipeline on
    device (~0.14 s/step ResNet-50 bs256), while the scanned loop runs
    ~2-3x slower per step (scan carry overhead dominates once dispatch
    latency is hidden) plus a 10x compile. run_steps pays off when the
    host must SYNC every step (per-step metrics/logging) — there the
    ~7ms/dispatch floor applies per step; the bench's deferred-fetch loop
    does not.

    BENCH_PREFETCH=1 (with SPD>1) additionally drives the
    production-shaped input path: per-step batches staged window-by-window
    through a DevicePrefetcher (feed_per_step windows, H2D overlapping
    compute) instead of one fixed device-resident feed.

    Returns (seconds, steps_actually_timed, executor, cold) — ``cold``
    carries the first-dispatch ``compile_seconds`` (trace + XLA compile,
    measured separately from the steady-state timing), ``cache_hit``
    (whether the persistent compile cache served it warm) and the
    window/prefetch shape fields (_cold_info)."""
    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    spd = int(os.environ.get("BENCH_SPD",
                             os.environ.get("PADDLE_TPU_SPD", "0") or "0")
              or 0)
    spd = max(1, min(spd, steps)) if spd > 0 else 1
    mesh_spec = os.environ.get(
        "BENCH_MESH", os.environ.get("PADDLE_TPU_MESH", "")).strip()
    if mesh_spec and not any(isinstance(v, tuple) for v in feed.values()):
        # sharded windowed path (LoD feeds need the per-step executor);
        # startup already ran above, so the scope state is live
        return _timed_run_mesh(fluid, loss, feed, steps, spd, mesh_spec)
    use_pf = spd > 1 and not any(isinstance(v, tuple) for v in feed.values()) \
        and os.environ.get("BENCH_PREFETCH", "").strip().lower() in ("1", "true")
    if on_accel and not use_pf:
        import jax

        from paddle_tpu.fluid import core as _core

        dev = _core.get_jax_device(place)
        # LoD feeds are (rows, lengths) tuples: stage only the rows array;
        # the lengths must stay host ints (the executor int()s each one —
        # device scalars there would mean per-element D2H syncs per step)
        feed = {k: ((jax.device_put(v[0], dev), v[1])
                    if isinstance(v, tuple) else jax.device_put(v, dev))
                for k, v in feed.items()}
    if spd > 1:
        n_chunks = max(1, steps // spd)
        steps = n_chunks * spd
        if use_pf:
            from paddle_tpu.fluid.prefetch import (DevicePrefetcher,
                                                   default_depth)

            depth = default_depth()
            batches = (dict(feed) for _ in range((n_chunks + 1) * spd))
            cc0 = _cache_counters()
            t_c = time.perf_counter()
            with DevicePrefetcher(batches, n_steps=spd, place=place,
                                  depth=depth) as pf:
                it = iter(pf)
                fd, cnt = next(it)
                exe.run_steps(prog, feed=fd, fetch_list=[loss],
                              n_steps=cnt, feed_per_step=True)
                cold = _cold_info(time.perf_counter() - t_c, cc0,
                                  _cache_counters(), spd, depth)
                t0 = time.perf_counter()
                out = None
                for _ in range(n_chunks):
                    fd, cnt = next(it)
                    (out,) = exe.run_steps(prog, feed=fd, fetch_list=[loss],
                                           n_steps=cnt, feed_per_step=True)
            last = float(np.asarray(out).reshape(-1)[0])
            dt = time.perf_counter() - t0
            assert np.isfinite(last), f"non-finite loss {last}"
            return dt, steps, exe, cold
        cc0 = _cache_counters()
        t_c = time.perf_counter()
        exe.run_steps(prog, feed=feed, fetch_list=[loss], n_steps=spd)
        cold = _cold_info(time.perf_counter() - t_c, cc0, _cache_counters(),
                          spd, 0)
        t0 = time.perf_counter()
        out = None
        for _ in range(n_chunks):
            (out,) = exe.run_steps(prog, feed=feed, fetch_list=[loss],
                                   n_steps=spd)
        last = float(np.asarray(out).reshape(-1)[0])
        dt = time.perf_counter() - t0
        assert np.isfinite(last), f"non-finite loss {last}"
        return dt, steps, exe, cold
    cc0 = _cache_counters()
    t_c = time.perf_counter()
    exe.run(prog, feed=feed, fetch_list=[loss])
    cold = _cold_info(time.perf_counter() - t_c, cc0, _cache_counters())
    for _ in range(max(0, warmup - 1)):
        exe.run(prog, feed=feed, fetch_list=[loss])
    # fetch device-resident losses per step (return_numpy=False defers the
    # D2H sync); materializing the LAST loss inside the timed region blocks
    # on the whole device queue, so the timing is honest while per-step
    # latency of the fetch transport overlaps with compute.
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        (out,) = exe.run(prog, feed=feed, fetch_list=[loss],
                         return_numpy=False)
    last = float(np.asarray(out).reshape(-1)[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(last), f"non-finite loss {last}"
    return dt, steps, exe, cold


def kernel_config():
    """The active kernel configuration, recorded in EVERY BENCH line so
    BENCH_r*.json rounds are attributable to kernel changes: ``flash``
    (Pallas flash attention on/off after the PADDLE_TPU_FLASH/attr/AUTO
    precedence) and ``fused`` (the pallas_fused families that would
    dispatch — softmax_xent + optimizer sweeps — under PADDLE_TPU_FUSED)."""
    try:
        from paddle_tpu.ops import pallas_fused
        from paddle_tpu.ops.attention_ops import _flash_decision

        return {"flash": bool(_flash_decision()),
                "fused": pallas_fused.active_families()}
    except Exception:
        return {"flash": False, "fused": []}


def result_line(name, value, unit, baseline_key, **extra):
    return {"metric": name, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(value / BASELINES[baseline_key], 3),
            **kernel_config(), **extra}


def _env_int(model, name, default):
    """Per-model override (BENCH_RESNET_BS) > generic (BENCH_BS) > default.
    In the default both-models mode the generic var would force one model's
    tuning onto the other, so per-model vars take precedence."""
    v = os.environ.get(f"BENCH_{model.upper()}_{name}",
                       os.environ.get(f"BENCH_{name}"))
    return int(v) if v else default


def bench_resnet(fluid, platform, on_accel):
    from paddle_tpu.models import resnet

    batch = _env_int("resnet", "BS", 256 if on_accel else 4)
    steps = _env_int("resnet", "STEPS", 20 if on_accel else 3)
    image_hw = 224 if on_accel else 64
    class_dim = 1000 if on_accel else 100

    img, label, prediction, loss, acc = resnet.build(
        class_dim=class_dim, depth=50, image_shape=(3, image_hw, image_hw),
        lr=0.1)
    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 3, image_hw, image_hw)).astype(np.float32),
            "label": rng.randint(0, class_dim, size=(batch, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)

    ips = batch * steps / dt
    # MFU input: ResNet-50 fwd ~3.86 GFLOP/img at 224px (scales ~(hw/224)^2);
    # train ~= 3x fwd.  Only meaningful on a real accelerator.
    extra = {"amp": fluid.amp.compute_dtype() or "off", **cold}
    if on_accel:
        import jax

        gflop_per_img = 3 * 3.86 * (image_hw / 224.0) ** 2
        tflops = ips * gflop_per_img / 1e3
        peak = peak_tflops(jax.devices()[0].device_kind)
        extra["achieved_tflops"] = round(tflops, 2)
        extra["mfu_pct"] = round(100.0 * tflops / peak, 2)
        extra["peak_tflops_assumed"] = peak
    return result_line(f"resnet50_{image_hw}px_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", "resnet", **extra)


def bench_transformer(fluid, platform, on_accel):
    from paddle_tpu.models import transformer

    batch = _env_int("transformer", "BS", 64 if on_accel else 2)
    steps = _env_int("transformer", "STEPS", 20 if on_accel else 3)
    seq_len = 256 if on_accel else 32
    cfg = (transformer.base_config() if on_accel
           else transformer.tiny_config())

    src, tgt, lbl, loss = transformer.build(
        cfg, src_len=seq_len, tgt_len=seq_len, lr=1e-3)
    rng = np.random.RandomState(0)
    feed = {"src_word": rng.randint(1, cfg.src_vocab_size, size=(batch, seq_len)).astype(np.int64),
            "tgt_word": rng.randint(1, cfg.tgt_vocab_size, size=(batch, seq_len)).astype(np.int64),
            "lbl_word": rng.randint(1, cfg.tgt_vocab_size, size=(batch, seq_len, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)

    tps = batch * seq_len * steps / dt  # target tokens/sec
    return result_line(
        f"transformer_{cfg.name}_len{seq_len}_bs{batch}_train_{platform}",
        tps, "tokens/sec/chip", "transformer",
        amp=fluid.amp.compute_dtype() or "off", **cold)


def bench_vgg(fluid, platform, on_accel):
    """VGG-19 training (BENCH_MODEL=vgg; baseline: the reference's
    published 28.46 images/sec at bs=64 on 2x Xeon 6148)."""
    from paddle_tpu.models import vgg

    batch = _env_int("vgg", "BS", 64 if on_accel else 4)
    steps = _env_int("vgg", "STEPS", 10 if on_accel else 3)
    image_hw = 224 if on_accel else 32
    class_dim = 1000 if on_accel else 10
    img, label, prediction, loss, acc = vgg.build(
        class_dim=class_dim, image_shape=(3, image_hw, image_hw), lr=0.01,
        depth=19)
    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 3, image_hw, image_hw))
            .astype(np.float32),
            "label": rng.randint(0, class_dim,
                                 size=(batch, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)
    ips = batch * steps / dt
    return result_line(f"vgg19_{image_hw}px_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", "vgg",
                       amp=fluid.amp.compute_dtype() or "off", **cold)


def bench_mnist(fluid, platform, on_accel):
    from paddle_tpu.models import mnist

    batch = _env_int("mnist", "BS", 512 if on_accel else 64)
    steps = _env_int("mnist", "STEPS", 50 if on_accel else 10)
    img, label, prediction, loss, acc = mnist.mlp()
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 784)).astype(np.float32),
            "label": rng.randint(0, 10, size=(batch, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)
    ips = batch * steps / dt
    return result_line(f"mnist_mlp_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", "mnist", **cold)


def bench_resnet_infer(fluid, platform, on_accel):
    """Inference throughput via the predictor path (ref baseline: ResNet-50
    infer bs16 = 217.69 images/sec on 2x Xeon 6148, IntelOptimizedPaddle
    .md:85-87).  Forward-only for_test clone, deferred fetches.
    BENCH_INT8=1 additionally rewrites the weights int8-in-HBM
    (transpiler.Int8WeightTranspiler) — the weight-bandwidth-bound
    deployment configuration."""
    from paddle_tpu.models import resnet

    batch = _env_int("resnet_infer", "BS", 16)
    steps = _env_int("resnet_infer", "STEPS", 30 if on_accel else 3)
    image_hw = 224 if on_accel else 64
    class_dim = 1000 if on_accel else 100
    img, label, prediction, loss, acc = resnet.build(
        class_dim=class_dim, depth=50, image_shape=(3, image_hw, image_hw),
        lr=0.1)
    infer_prog = fluid.default_main_program().clone(for_test=True)
    int8 = os.environ.get("BENCH_INT8", "") in ("1", "true")

    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    if int8:
        # AFTER startup: the transpiler quantizes the weights that now
        # live in the scope (before startup there is nothing to quantize
        # and every param would be silently skipped)
        from paddle_tpu.fluid.transpiler import Int8WeightTranspiler

        quantized = Int8WeightTranspiler().transpile(infer_prog)
        assert quantized, "int8 transpile quantized no weights"
    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 3, image_hw, image_hw))
            .astype(np.float32)}
    if on_accel:
        import jax

        from paddle_tpu.fluid import core as _core

        dev = _core.get_jax_device(place)
        # LoD feeds are (rows, lengths) tuples: stage only the rows array;
        # the lengths must stay host ints (the executor int()s each one —
        # device scalars there would mean per-element D2H syncs per step)
        feed = {k: ((jax.device_put(v[0], dev), v[1])
                    if isinstance(v, tuple) else jax.device_put(v, dev))
                for k, v in feed.items()}
    cc0 = _cache_counters()
    t_c = time.perf_counter()
    exe.run(infer_prog, feed=feed, fetch_list=[prediction])
    cold = _cold_info(time.perf_counter() - t_c, cc0, _cache_counters())
    exe.run(infer_prog, feed=feed, fetch_list=[prediction])
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        (out,) = exe.run(infer_prog, feed=feed, fetch_list=[prediction],
                         return_numpy=False)
    last = np.asarray(out)
    dt = time.perf_counter() - t0
    assert np.isfinite(last).all()
    ips = batch * steps / dt
    tag = "_int8" if int8 else ""
    return result_line(
        f"resnet50_{image_hw}px_bs{batch}_infer{tag}_{platform}",
        ips, "images/sec/chip", "resnet_infer",
        amp=fluid.amp.compute_dtype() or "off",
        weights=("int8" if int8 else "fp32"), **cold)


def bench_decode(fluid, platform, on_accel):
    """Beam-search GENERATION throughput (BENCH_MODEL=decode).

    Default engine: JitBeamSearchDecoder — the WHOLE generation loop is one
    lax.while_loop XLA program (2 dispatches total: loop + LoD packaging),
    the VERDICT r4 missing-#1 path.  BENCH_DECODE_ENGINE=eager selects the
    legacy While-loop BeamSearchDecoder (per-op dispatches per step) for
    comparison.  No reference decode-throughput figure exists, so
    vs_baseline is reported as 0 and the metric stands on its absolute
    tokens/sec."""
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.contrib.decoder import (BeamSearchDecoder,
                                                  InitState,
                                                  JitBeamSearchDecoder,
                                                  StateCell)

    engine = os.environ.get("BENCH_DECODE_ENGINE", "jit")
    decoder_cls = BeamSearchDecoder if engine == "eager" \
        else JitBeamSearchDecoder

    batch = _env_int("decode", "BS", 8)
    rounds = _env_int("decode", "STEPS", 3)
    v, d = 1000, 64
    max_len, beam = 16, 4

    src = layers.data(name="src", shape=[1], dtype="int64")
    h0 = layers.fc(input=layers.embedding(src, size=[v, d]), size=d,
                   act="tanh")
    cell = StateCell(inputs={"x": None},
                     states={"h": InitState(init=h0, need_reorder=True)},
                     out_state="h")

    @cell.state_updater
    def updater(c):
        c.set_state("h", layers.fc(input=[c.get_input("x"),
                                          c.get_state("h")],
                                   size=d, act="tanh"))

    init_ids = layers.data(name="init_ids", shape=[1], dtype="int64",
                           lod_level=2)
    init_scores = layers.data(name="init_scores", shape=[1],
                              dtype="float32", lod_level=2)
    dec = decoder_cls(cell, init_ids, init_scores,
                      target_dict_dim=v, word_dim=d, topk_size=50,
                      sparse_emb=False, max_len=max_len,
                      beam_size=beam, end_id=1)
    dec.decode()
    out_ids, _ = dec()

    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    int8 = os.environ.get("BENCH_INT8", "") in ("1", "true")
    if int8:
        # weight-only int8 under the compiled decode loop: embeddings +
        # projection stream int8 from HBM, dequant fused at the consumer
        from paddle_tpu.fluid.transpiler.int8_transpiler import (
            Int8WeightTranspiler)
        quantized = Int8WeightTranspiler().transpile(
            fluid.default_main_program())
        assert quantized, "int8 transpile quantized no weights"
    rng = np.random.RandomState(0)
    lod2 = [[1] * batch, [1] * batch]
    feed = {"src": rng.randint(2, v, size=(batch, 1)).astype(np.int64),
            "init_ids": fluid.create_lod_tensor(
                np.zeros((batch, 1), np.int64), lod2),
            "init_scores": fluid.create_lod_tensor(
                np.zeros((batch, 1), np.float32), lod2)}
    cc0 = _cache_counters()
    t_c = time.perf_counter()
    (warm,) = exe.run(fluid.default_main_program(), feed=feed,
                      fetch_list=[out_ids], return_numpy=False)
    cold = _cold_info(time.perf_counter() - t_c, cc0, _cache_counters())
    t0 = time.perf_counter()
    n_tokens = 0
    for _ in range(rounds):
        (ids,) = exe.run(fluid.default_main_program(), feed=feed,
                         fetch_list=[out_ids], return_numpy=False)
        n_tokens += int(np.asarray(ids).size)
    dt = time.perf_counter() - t0
    return {"metric": f"beam_decode_b{batch}_beam{beam}_len{max_len}"
                      f"_{engine}{'_int8' if int8 else ''}_{platform}",
            "value": round(n_tokens / dt, 2), "unit": "tokens/sec/chip",
            "vs_baseline": 0.0, **kernel_config(), **cold,
            "note": "no published reference decode throughput; absolute "
                    "generation rate ("
                    + ("one compiled while_loop program"
                       if engine != "eager" else "eager-island execution")
                    + ")"}


def _bench_v2_image(model, fluid, platform, on_accel, ref_hw):
    """AlexNet/GoogleNet via their legacy-DSL configs (benchmark/v2/) —
    the configs themselves are the reference's; baselines are the
    published bs=64 CPU training rates (IntelOptimizedPaddle.md)."""
    from paddle_tpu.trainer_config_helpers import (
        build_settings_optimizer, get_outputs, set_config_args)

    batch = _env_int(model, "BS", 64 if on_accel else 4)
    steps = _env_int(model, "STEPS", 10 if on_accel else 3)
    # CPU fallback geometries keep every pool non-degenerate
    hw = ref_hw if on_accel else (67 if model == "alexnet" else 64)
    class_dim = 1000 if on_accel else 10
    set_config_args(height=hw, width=hw, num_class=class_dim,
                    batch_size=batch, is_infer=False)
    path = os.path.join(REPO, "benchmark", "v2", f"{model}.py")
    with open(path) as f:
        exec(compile(f.read(), path, "exec"), {"__name__": "config"})
    (loss,) = get_outputs()
    build_settings_optimizer().minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"data": rng.normal(size=(batch, 3 * hw * hw)).astype(np.float32),
            "label": rng.randint(0, class_dim,
                                 size=(batch, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)
    ips = batch * steps / dt
    return result_line(f"{model}_{hw}px_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", model,
                       amp=fluid.amp.compute_dtype() or "off", **cold)


def bench_rnn(fluid, platform, on_accel):
    """IMDB-style LSTM training via the legacy-DSL rnn config
    (benchmark/v2/rnn.py == the reference benchmark/paddle/rnn/rnn.py
    structure).  Fixed-length sequences (the config's pad_seq=True
    regime: one compiled shape).  Baseline: LSTM 2-layer h=512 bs=64 at
    184 ms/batch -> 347.8 sequences/sec."""
    from paddle_tpu.trainer_config_helpers import (
        build_settings_optimizer, get_outputs, set_config_args)

    batch = _env_int("rnn", "BS", 64 if on_accel else 8)
    steps = _env_int("rnn", "STEPS", 10 if on_accel else 3)
    hidden = 512 if on_accel else 32
    seqlen = 100 if on_accel else 10
    vocab = 30000 if on_accel else 100
    set_config_args(vocab_size=vocab, hidden_size=hidden, lstm_num=2,
                    emb_size=128 if on_accel else 16, batch_size=batch)
    path = os.path.join(REPO, "benchmark", "v2", "rnn.py")
    with open(path) as f:
        exec(compile(f.read(), path, "exec"), {"__name__": "config"})
    (loss,) = get_outputs()
    build_settings_optimizer().minimize(loss)

    rng = np.random.RandomState(0)
    rows = rng.randint(1, vocab, size=(batch * seqlen, 1)).astype(np.int64)
    feed = {"data": (rows, [[seqlen] * batch]),
            "label": rng.randint(0, 2, size=(batch, 1)).astype(np.int64)}
    dt, steps, _, cold = timed_run(fluid, on_accel, loss, feed, steps)
    sps = batch * steps / dt
    return result_line(f"rnn_lstm2_h{hidden}_len{seqlen}_bs{batch}"
                       f"_train_{platform}", sps, "sequences/sec/chip",
                       "rnn", amp=fluid.amp.compute_dtype() or "off",
                       **cold)


def bench_alexnet(fluid, platform, on_accel):
    return _bench_v2_image("alexnet", fluid, platform, on_accel, 227)


def bench_googlenet(fluid, platform, on_accel):
    return _bench_v2_image("googlenet", fluid, platform, on_accel, 224)


BENCHES = {"resnet": bench_resnet, "transformer": bench_transformer,
           "mnist": bench_mnist, "resnet_infer": bench_resnet_infer,
           "decode": bench_decode, "vgg": bench_vgg,
           "alexnet": bench_alexnet, "googlenet": bench_googlenet,
           "rnn": bench_rnn}


def _run_one(model, fluid, platform, on_accel):
    """Run one bench in a fresh default program; returns its result dict
    (or an error dict — a failing model must not silence the others)."""
    import paddle_tpu.fluid.framework as fw

    with fw.program_guard(fw.Program(), fw.Program()):
        with fluid.scope_guard(fluid.Scope()):
            try:
                return BENCHES[model](fluid, platform, on_accel)
            except Exception as exc:
                return {"metric": f"{model}_failed_{platform}", "value": 0,
                        "unit": "none", "vs_baseline": 0,
                        "error": f"{type(exc).__name__}: {exc}",
                        "trace": traceback.format_exc(limit=5)}


def main():
    # flash auto-defaults ON for TPU backends, but this bench usually runs
    # over the axon tunnel, which cannot remote-compile Mosaic kernels —
    # keep the XLA attention path unless BENCH_FLASH=1 explicitly opts in
    # (on a real TPU VM, set it: the Pallas path is the fast one).
    if os.environ.get("BENCH_FLASH", "").strip().lower() in ("1", "true"):
        os.environ.setdefault("PADDLE_TPU_FLASH", "1")
    else:
        os.environ.setdefault("PADDLE_TPU_FLASH", "0")
    # same contract for the fused softmax-xent/optimizer kernels: the axon
    # tunnel cannot remote-compile Mosaic either, so BENCH_FUSED=1 opts in
    # explicitly (on a real TPU VM, set it: the fused path is the fast one)
    if os.environ.get("BENCH_FUSED", "").strip().lower() in ("1", "true"):
        os.environ.setdefault("PADDLE_TPU_FUSED", "1")
    else:
        os.environ.setdefault("PADDLE_TPU_FUSED", "0")
    model = os.environ.get("BENCH_MODEL", "")
    for i, a in enumerate(sys.argv):
        if a == "--model" and i + 1 < len(sys.argv):
            model = sys.argv[i + 1]
        elif a.startswith("--model="):
            model = a.split("=", 1)[1]
    if model and model not in BENCHES:
        print(json.dumps({"metric": f"unknown_model_{model}", "value": 0,
                          "unit": "none", "vs_baseline": 0,
                          "error": f"BENCH_MODEL must be one of {sorted(BENCHES)}"}))
        return 1

    platform, probe_status = probe_platform(
        timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "180")))
    import jax
    if platform == "cpu":
        # Default backend unusable (or genuinely CPU): pin to CPU so the
        # in-process backend cannot hang the way the probe did.
        jax.config.update("jax_platforms", "cpu")
    on_accel = platform not in ("cpu",)

    try:
        import paddle_tpu.fluid as fluid
    except Exception as exc:
        print(json.dumps({
            "metric": f"import_failed_{platform}", "value": 0,
            "unit": "none", "vs_baseline": 0,
            "error": f"{type(exc).__name__}: {exc}",
            "trace": traceback.format_exc(limit=5)}))
        return 1

    if on_accel and os.environ.get("BENCH_AMP", "1") != "0":
        # keep-low activations: contraction outputs stay bf16 so
        # inter-layer HBM traffic halves (norm statistics and the loss
        # boundary remain fp32 — fluid/amp.py).  Measured on the r5
        # tunnel: ResNet-50 2325 img/s vs 1857 with fp32-restore
        # activations (+25%).  Opt out via BENCH_AMP_KEEP=0 (bench knob)
        # or PADDLE_TPU_AMP_KEEP=0 (the library-wide knob, honored when
        # the bench one is unset).
        keep_env = os.environ.get("BENCH_AMP_KEEP",
                                  os.environ.get("PADDLE_TPU_AMP_KEEP", "1"))
        keep = keep_env.strip().lower() not in ("0", "false")
        fluid.amp.enable("bfloat16", keep_activations=keep)

    if model:  # single-model mode
        result = _run_one(model, fluid, platform, on_accel)
        if probe_status != "ok":
            result["tpu_probe"] = probe_status
        print(json.dumps(result))
        return 0 if "error" not in result else 1

    # Default: BOTH driver metrics (BASELINE.json: ResNet-50 images/sec/chip
    # AND Transformer-base tokens/sec/chip), one line each, then a combined
    # final line so a last-line-only parser still sees both numbers.
    res = _run_one("resnet", fluid, platform, on_accel)
    print(json.dumps(res), flush=True)
    trf = _run_one("transformer", fluid, platform, on_accel)
    print(json.dumps(trf), flush=True)

    combined = dict(res)
    if probe_status != "ok":
        combined["tpu_probe"] = probe_status
    if "error" in trf:
        combined["transformer_error"] = trf.get("error")
    else:
        combined["transformer_metric"] = trf["metric"]
        combined["transformer_tokens_per_sec_chip"] = trf["value"]
        combined["transformer_vs_baseline"] = trf["vs_baseline"]
    print(json.dumps(combined))
    return 0 if ("error" not in res and "error" not in trf) else 1


if __name__ == "__main__":
    sys.exit(main())
