"""Benchmark: ResNet-50 ImageNet training throughput, images/sec/chip.

Matches the driver metric (BASELINE.json: "ResNet-50 images/sec/chip").
vs_baseline compares against the reference's best published ResNet-50
*training* number: 84.08 images/sec on 2x Xeon 6148 with MKL-DNN at bs=256
(reference benchmark/IntelOptimizedPaddle.md:43-45; the repo publishes no GPU
or per-chip ResNet-50 training figure).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_RESNET50_TRAIN_IPS = 84.08


def main():
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    batch = int(os.environ.get("BENCH_BS", "128" if on_accel else "8"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_accel else "3"))
    image_hw = 224 if on_accel else 64
    class_dim = 1000 if on_accel else 100

    img, label, prediction, loss, acc = resnet.build(
        class_dim=class_dim, depth=50, image_shape=(3, image_hw, image_hw),
        lr=0.1)

    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    x = rng.normal(size=(batch, 3, image_hw, image_hw)).astype(np.float32)
    y = rng.randint(0, class_dim, size=(batch, 1)).astype(np.int64)

    prog = fluid.default_main_program()
    # warmup: compile + 2 steps
    for _ in range(2):
        exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])

    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(prog, feed={"img": x, "label": y}, fetch_list=[loss])
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": f"resnet50_{image_hw}px_bs{batch}_train_{platform}",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / REFERENCE_RESNET50_TRAIN_IPS, 3),
    }))


if __name__ == "__main__":
    main()
