"""Benchmark driver: prints ONE JSON line with the headline metric.

Default model is ResNet-50 training throughput (images/sec/chip), matching
the driver metric (BASELINE.json: "ResNet-50 images/sec/chip").  Set
BENCH_MODEL=transformer for Transformer-base tokens/sec/chip (the second
driver metric), BENCH_MODEL=mnist for the MLP sanity config.

vs_baseline compares against the reference's best published number for the
model (reference benchmark/IntelOptimizedPaddle.md:43-45 — ResNet-50
training 84.08 images/sec on 2x Xeon 6148 MKL-DNN bs=256; the reference
publishes no per-chip TPU or Transformer figure, so the Transformer baseline
is the same hardware-era proxy documented in BASELINE.md).

Hardening (round-1 postmortem): the TPU backend behind the `axon` tunnel can
HANG on first use, not just error — so the platform is probed in a
subprocess with a timeout, and on probe failure the bench falls back to CPU
via jax.config.update (env vars are too late: sitecustomize pre-imports
jax).  Every failure path still emits one JSON diagnostic line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Reference numbers to compare against (see module docstring).
BASELINES = {
    "resnet": 84.08,        # images/sec, ResNet-50 train bs=256, 2x Xeon 6148
    "transformer": 1655.0,  # tokens/sec proxy: LSTM h=1280 bs=256 is the only
                            # published seq2seq-scale figure (BASELINE.md); the
                            # reference has no Transformer number.
    "mnist": 10000.0,       # images/sec, no published figure; nominal.
}

PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256), jnp.bfloat16);"
    "v = (x @ x).sum();"
    "print('PROBE_OK', jax.devices()[0].platform, float(v))"
)


def probe_platform(timeout: float = 180.0) -> str:
    """Run a tiny jitted matmul in a subprocess; return its platform.

    Returns 'cpu' if the default backend fails to initialise or hangs
    (the axon tunnel wedges rather than erroring, so an in-process
    try/except cannot catch it).
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=timeout)
        for line in out.stdout.splitlines():
            if line.startswith("PROBE_OK"):
                return line.split()[1]
    except (subprocess.TimeoutExpired, OSError):
        pass
    return "cpu"


def timed_run(fluid, on_accel, loss, feed, steps, warmup=2):
    """Shared harness: startup program, warmup (compile), timed steps.

    Returns (seconds, executor) for `steps` timed executions."""
    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    for _ in range(warmup):
        exe.run(prog, feed=feed, fetch_list=[loss])
    t0 = time.perf_counter()
    for _ in range(steps):
        exe.run(prog, feed=feed, fetch_list=[loss])
    return time.perf_counter() - t0, exe


def result_line(name, value, unit, baseline_key, **extra):
    return {"metric": name, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(value / BASELINES[baseline_key], 3), **extra}


def bench_resnet(fluid, platform, on_accel):
    from paddle_tpu.models import resnet

    batch = int(os.environ.get("BENCH_BS", "128" if on_accel else "4"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_accel else "3"))
    image_hw = 224 if on_accel else 64
    class_dim = 1000 if on_accel else 100

    img, label, prediction, loss, acc = resnet.build(
        class_dim=class_dim, depth=50, image_shape=(3, image_hw, image_hw),
        lr=0.1)
    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 3, image_hw, image_hw)).astype(np.float32),
            "label": rng.randint(0, class_dim, size=(batch, 1)).astype(np.int64)}
    dt, _ = timed_run(fluid, on_accel, loss, feed, steps)

    ips = batch * steps / dt
    # MFU input: ResNet-50 fwd ~3.86 GFLOP/img at 224px (scales ~(hw/224)^2);
    # train ~= 3x fwd.  Only meaningful on a real accelerator.
    extra = {}
    if on_accel:
        gflop_per_img = 3 * 3.86 * (image_hw / 224.0) ** 2
        extra["achieved_tflops"] = round(ips * gflop_per_img / 1e3, 2)
    return result_line(f"resnet50_{image_hw}px_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", "resnet", **extra)


def bench_transformer(fluid, platform, on_accel):
    from paddle_tpu.models import transformer

    batch = int(os.environ.get("BENCH_BS", "32" if on_accel else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_accel else "3"))
    seq_len = 256 if on_accel else 32
    cfg = (transformer.base_config() if on_accel
           else transformer.tiny_config())

    src, tgt, lbl, loss = transformer.build(
        cfg, src_len=seq_len, tgt_len=seq_len, lr=1e-3)
    rng = np.random.RandomState(0)
    feed = {"src_word": rng.randint(1, cfg.src_vocab_size, size=(batch, seq_len)).astype(np.int64),
            "tgt_word": rng.randint(1, cfg.tgt_vocab_size, size=(batch, seq_len)).astype(np.int64),
            "lbl_word": rng.randint(1, cfg.tgt_vocab_size, size=(batch, seq_len, 1)).astype(np.int64)}
    dt, _ = timed_run(fluid, on_accel, loss, feed, steps)

    tps = batch * seq_len * steps / dt  # target tokens/sec
    return result_line(
        f"transformer_{cfg.name}_len{seq_len}_bs{batch}_train_{platform}",
        tps, "tokens/sec/chip", "transformer")


def bench_mnist(fluid, platform, on_accel):
    from paddle_tpu.models import mnist

    batch = int(os.environ.get("BENCH_BS", "512" if on_accel else "64"))
    steps = int(os.environ.get("BENCH_STEPS", "50" if on_accel else "10"))
    img, label, prediction, loss, acc = mnist.mlp()
    fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"img": rng.normal(size=(batch, 784)).astype(np.float32),
            "label": rng.randint(0, 10, size=(batch, 1)).astype(np.int64)}
    dt, _ = timed_run(fluid, on_accel, loss, feed, steps)
    ips = batch * steps / dt
    return result_line(f"mnist_mlp_bs{batch}_train_{platform}",
                       ips, "images/sec/chip", "mnist")


BENCHES = {"resnet": bench_resnet, "transformer": bench_transformer,
           "mnist": bench_mnist}


def main():
    model = os.environ.get("BENCH_MODEL", "resnet")
    for i, a in enumerate(sys.argv):
        if a == "--model" and i + 1 < len(sys.argv):
            model = sys.argv[i + 1]
        elif a.startswith("--model="):
            model = a.split("=", 1)[1]
    if model not in BENCHES:
        print(json.dumps({"metric": f"unknown_model_{model}", "value": 0,
                          "unit": "none", "vs_baseline": 0,
                          "error": f"BENCH_MODEL must be one of {sorted(BENCHES)}"}))
        return 1

    platform = probe_platform(
        timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "180")))
    import jax
    if platform == "cpu":
        # Default backend unusable (or genuinely CPU): pin to CPU so the
        # in-process backend cannot hang the way the probe did.
        jax.config.update("jax_platforms", "cpu")
    on_accel = platform not in ("cpu",)

    try:
        import paddle_tpu.fluid as fluid
        result = BENCHES[model](fluid, platform, on_accel)
        print(json.dumps(result))
        return 0
    except Exception as exc:  # emit a diagnostic JSON line, never die silently
        print(json.dumps({
            "metric": f"{model}_failed_{platform}", "value": 0,
            "unit": "none", "vs_baseline": 0,
            "error": f"{type(exc).__name__}: {exc}",
            "trace": traceback.format_exc(limit=5),
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
