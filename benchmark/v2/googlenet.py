#!/usr/bin/env python
"""GoogleNet-v1 config in the legacy trainer_config_helpers DSL (ref
config: benchmark/paddle/image/googlenet.py — same inception
(1x1 / 3x3r+3x3 / 5x5r+5x5 / pool+proj -> concat) structure; BASELINE.md
rows: 1149 ms/batch bs128 GPU-era, 250-270 images/sec CPU train)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

height = get_config_arg("height", int, 224)
width = get_config_arg("width", int, 224)
num_class = get_config_arg("num_class", int, 1000)
batch_size = get_config_arg("batch_size", int, 128)
is_infer = get_config_arg("is_infer", bool, False)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider", obj="process", args={})

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size))


def inception(name, input, channels, f1, f3r, f3, f5r, f5, proj):
    cov1 = img_conv_layer(name=name + "_1", input=input, filter_size=1,
                          num_channels=channels, num_filters=f1, stride=1,
                          padding=0)
    cov3r = img_conv_layer(name=name + "_3r", input=input, filter_size=1,
                           num_channels=channels, num_filters=f3r,
                           stride=1, padding=0)
    cov3 = img_conv_layer(name=name + "_3", input=cov3r, filter_size=3,
                          num_filters=f3, stride=1, padding=1)
    cov5r = img_conv_layer(name=name + "_5r", input=input, filter_size=1,
                           num_channels=channels, num_filters=f5r,
                           stride=1, padding=0)
    cov5 = img_conv_layer(name=name + "_5", input=cov5r, filter_size=5,
                          num_filters=f5, stride=1, padding=2)
    pool = img_pool_layer(name=name + "_max", input=input, pool_size=3,
                          num_channels=channels, stride=1, padding=1)
    covprj = img_conv_layer(name=name + "_proj", input=pool,
                            filter_size=1, num_filters=proj, stride=1,
                            padding=0)
    return concat_layer(name=name, input=[cov1, cov3, cov5, covprj])


img = data_layer("data", size=height * width * 3, height=height,
                 width=width)
conv1 = img_conv_layer(name="conv1", input=img, filter_size=7,
                       num_channels=3, num_filters=64, stride=2, padding=3)
pool1 = img_pool_layer(name="pool1", input=conv1, pool_size=3, stride=2)
norm1 = img_cmrnorm_layer(input=pool1, size=5, scale=0.0001, power=0.75)
conv2r = img_conv_layer(name="conv2r", input=norm1, filter_size=1,
                        num_filters=64, stride=1, padding=0)
conv2 = img_conv_layer(name="conv2", input=conv2r, filter_size=3,
                       num_filters=192, stride=1, padding=1)
norm2 = img_cmrnorm_layer(input=conv2, size=5, scale=0.0001, power=0.75)
pool2 = img_pool_layer(name="pool2", input=norm2, pool_size=3, stride=2)

ince3a = inception("ince3a", pool2, 192, 64, 96, 128, 16, 32, 32)
ince3b = inception("ince3b", ince3a, 256, 128, 128, 192, 32, 96, 64)
pool3 = img_pool_layer(name="pool3", input=ince3b, pool_size=3, stride=2)
ince4a = inception("ince4a", pool3, 480, 192, 96, 208, 16, 48, 64)
ince4b = inception("ince4b", ince4a, 512, 160, 112, 224, 24, 64, 64)
ince4c = inception("ince4c", ince4b, 512, 128, 128, 256, 24, 64, 64)
ince4d = inception("ince4d", ince4c, 512, 112, 144, 288, 32, 64, 64)
ince4e = inception("ince4e", ince4d, 528, 256, 160, 320, 32, 128, 128)
pool4 = img_pool_layer(name="pool4", input=ince4e, pool_size=3, stride=2)
ince5a = inception("ince5a", pool4, 832, 256, 160, 320, 32, 128, 128)
ince5b = inception("ince5b", ince5a, 832, 384, 192, 384, 48, 128, 128)

# global average pool: size from the actual surviving spatial extent so
# the same config serves 224px runs and small smoke geometries
pool5 = img_pool_layer(name="pool5", input=ince5b,
                       pool_size=int(ince5b.shape[2]), stride=1,
                       pool_type=AvgPooling())
drop = dropout_layer(input=pool5, dropout_rate=0.4)
out = fc_layer(input=drop, size=num_class, act=SoftmaxActivation())

if is_infer:
    outputs(out)
else:
    lbl = data_layer(name="label", size=num_class)
    loss = cross_entropy(name="loss", input=out, label=lbl)
    outputs(loss)
