#!/usr/bin/env python
"""VGG config in the legacy trainer_config_helpers DSL, lowered onto the
TPU Fluid substrate (ref config: benchmark/paddle/image/vgg.py — same
structure and defaults; geometry/class-count readable from config args so
the same file drives ImageNet-scale runs and small smoke tests)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

height = get_config_arg("height", int, 224)
width = get_config_arg("width", int, 224)
num_class = get_config_arg("num_class", int, 1000)
batch_size = get_config_arg("batch_size", int, 64)
layer_num = get_config_arg("layer_num", int, 19)
is_infer = get_config_arg("is_infer", bool, False)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider", obj="process", args={})

settings(
    batch_size=batch_size,
    learning_rate=0.001 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size))

img = data_layer(name="image", size=height * width * 3,
                 height=height, width=width)


def vgg_network(vgg_num=3):
    tmp = img_conv_group(
        input=img, num_channels=3, conv_padding=1,
        conv_num_filter=[64, 64], conv_filter_size=3,
        conv_act=ReluActivation(), pool_size=2, pool_stride=2,
        pool_type=MaxPooling())
    tmp = img_conv_group(
        input=tmp, conv_num_filter=[128, 128], conv_padding=1,
        conv_filter_size=3, conv_act=ReluActivation(), pool_stride=2,
        pool_type=MaxPooling(), pool_size=2)
    for width_ in (256, 512, 512):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[width_] * vgg_num, conv_padding=1,
            conv_filter_size=3, conv_act=ReluActivation(), pool_stride=2,
            pool_type=MaxPooling(), pool_size=2)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    return fc_layer(input=tmp, size=num_class, act=SoftmaxActivation())


# 16/19 are the reference depths; 11 (vgg_num=1) is a smoke-test depth
vgg = vgg_network({16: 3, 19: 4, 11: 1}[layer_num])

if is_infer:
    outputs(vgg)
else:
    lbl = data_layer(name="label", size=num_class)
    loss = cross_entropy(name="loss", input=vgg, label=lbl)
    outputs(loss)
