#!/usr/bin/env python
"""IMDB sentiment LSTM config in the legacy trainer_config_helpers DSL
(ref config: benchmark/paddle/rnn/rnn.py — embedding -> stacked
simple_lstm -> last_seq -> softmax fc; vocab/hidden/lstm_num readable from
config args; BASELINE.md row: LSTM h=512 at 184 ms/batch bs=64 is the
published era figure for this family)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

num_class = get_config_arg("num_class", int, 2)
vocab_size = get_config_arg("vocab_size", int, 30000)
batch_size = get_config_arg("batch_size", int, 128)
lstm_num = get_config_arg("lstm_num", int, 1)
hidden_size = get_config_arg("hidden_size", int, 128)
emb_size = get_config_arg("emb_size", int, 128)

define_py_data_sources2(
    "train.list", None, module="provider", obj="process",
    args={"vocab_size": vocab_size})

settings(
    batch_size=batch_size,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

net = data_layer("data", size=vocab_size)
net = embedding_layer(input=net, size=emb_size)

for _ in range(lstm_num):
    net = simple_lstm(input=net, size=hidden_size)

net = last_seq(input=net)
net = fc_layer(input=net, size=num_class, act=SoftmaxActivation())

lab = data_layer("label", num_class)
loss = classification_cost(input=net, label=lab)
outputs(loss)
