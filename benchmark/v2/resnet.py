#!/usr/bin/env python
"""ResNet config in the legacy trainer_config_helpers DSL, lowered onto
the TPU Fluid substrate (ref config: benchmark/paddle/image/resnet.py —
same bottleneck/projection structure; geometry/class-count/block-depth
readable from config args so one file serves ImageNet runs and smoke
tests)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

height = get_config_arg("height", int, 224)
width = get_config_arg("width", int, 224)
num_class = get_config_arg("num_class", int, 1000)
batch_size = get_config_arg("batch_size", int, 64)
layer_num = get_config_arg("layer_num", int, 50)
is_infer = get_config_arg("is_infer", bool, False)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider", obj="process", args={})

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size))


def conv_bn_layer(name, input, filter_size, num_filters, stride, padding,
                  channels=None, active_type=ReluActivation()):
    tmp = img_conv_layer(name=name + "_conv", input=input,
                         filter_size=filter_size, num_channels=channels,
                         num_filters=num_filters, stride=stride,
                         padding=padding, act=LinearActivation(),
                         bias_attr=False)
    return batch_norm_layer(name=name + "_bn", input=tmp, act=active_type,
                            use_global_stats=is_infer)


def bottleneck_block(name, input, num_filters1, num_filters2):
    tmp = conv_bn_layer(name + "_branch2a", input, 1, num_filters1, 1, 0)
    tmp = conv_bn_layer(name + "_branch2b", tmp, 3, num_filters1, 1, 1)
    tmp = conv_bn_layer(name + "_branch2c", tmp, 1, num_filters2, 1, 0,
                        active_type=LinearActivation())
    return addto_layer(name=name + "_addto", input=[input, tmp],
                       act=ReluActivation())


def mid_projection(name, input, num_filters1, num_filters2, stride=2):
    branch1 = conv_bn_layer(name + "_branch1", input, 1, num_filters2,
                            stride, 0, active_type=LinearActivation())
    tmp = conv_bn_layer(name + "_branch2a", input, 1, num_filters1,
                        stride, 0)
    tmp = conv_bn_layer(name + "_branch2b", tmp, 3, num_filters1, 1, 1)
    tmp = conv_bn_layer(name + "_branch2c", tmp, 1, num_filters2, 1, 0,
                        active_type=LinearActivation())
    return addto_layer(name=name + "_addto", input=[branch1, tmp],
                       act=ReluActivation())


img = data_layer(name="image", size=height * width * 3,
                 height=height, width=width)


def deep_res_net(res2_num=3, res3_num=4, res4_num=6, res5_num=3):
    tmp = conv_bn_layer("conv1", img, 7, 64, 2, 3, channels=3)
    tmp = img_pool_layer(name="pool1", input=tmp, pool_size=3, stride=2)
    stages = [(res2_num, 64, 256, 1), (res3_num, 128, 512, 2),
              (res4_num, 256, 1024, 2), (res5_num, 512, 2048, 2)]
    for si, (blocks, f1, f2, stride) in enumerate(stages, start=2):
        tmp = mid_projection(f"res{si}_1", tmp, f1, f2, stride=stride)
        for b in range(2, blocks + 1):
            tmp = bottleneck_block(f"res{si}_{b}", tmp, f1, f2)
    pool_hw = max(1, height // 32)
    tmp = img_pool_layer(name="avgpool", input=tmp, pool_size=pool_hw,
                         stride=1, pool_type=AvgPooling())
    return fc_layer(input=tmp, size=num_class, act=SoftmaxActivation())


_depths = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3),
           # small depths for smoke tests via config args
           14: (1, 1, 1, 1), 26: (2, 2, 2, 2)}
resnet = deep_res_net(*_depths[layer_num])

if is_infer:
    outputs(resnet)
else:
    lbl = data_layer(name="label", size=num_class)
    loss = cross_entropy(name="loss", input=resnet, label=lbl)
    outputs(loss)
