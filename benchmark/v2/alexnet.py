#!/usr/bin/env python
"""AlexNet config in the legacy trainer_config_helpers DSL (ref config:
benchmark/paddle/image/alexnet.py — same conv/LRN/pool chain; geometry and
class count readable from config args; BASELINE.md rows: 334 ms/batch
bs128 GPU-era, 399-626 images/sec CPU train)."""

from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403

height = get_config_arg("height", int, 227)
width = get_config_arg("width", int, 227)
num_class = get_config_arg("num_class", int, 1000)
batch_size = get_config_arg("batch_size", int, 128)
gp = get_config_arg("layer_num", int, 1)  # conv groups, as the ref config
is_infer = get_config_arg("is_infer", bool, False)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider", obj="process", args={})

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size))

net = data_layer("data", size=height * width * 3, height=height,
                 width=width)
# conv1 (implicit relu via the DSL's wrap_act_default semantics)
net = img_conv_layer(input=net, filter_size=11, num_channels=3,
                     num_filters=96, stride=4, padding=1)
net = img_cmrnorm_layer(input=net, size=5, scale=0.0001, power=0.75)
net = img_pool_layer(input=net, pool_size=3, stride=2)
# conv2
net = img_conv_layer(input=net, filter_size=5, num_filters=256, stride=1,
                     padding=2, groups=gp)
net = img_cmrnorm_layer(input=net, size=5, scale=0.0001, power=0.75)
net = img_pool_layer(input=net, pool_size=3, stride=2)
# conv3-5
net = img_conv_layer(input=net, filter_size=3, num_filters=384, stride=1,
                     padding=1)
net = img_conv_layer(input=net, filter_size=3, num_filters=384, stride=1,
                     padding=1, groups=gp)
net = img_conv_layer(input=net, filter_size=3, num_filters=256, stride=1,
                     padding=1, groups=gp)
net = img_pool_layer(input=net, pool_size=3, stride=2)

net = fc_layer(input=net, size=4096, act=ReluActivation(),
               layer_attr=ExtraAttr(drop_rate=0.5))
net = fc_layer(input=net, size=4096, act=ReluActivation(),
               layer_attr=ExtraAttr(drop_rate=0.5))
out = fc_layer(input=net, size=num_class, act=SoftmaxActivation())

if is_infer:
    outputs(out)
else:
    lbl = data_layer(name="label", size=num_class)
    loss = cross_entropy(name="loss", input=out, label=lbl)
    outputs(loss)
