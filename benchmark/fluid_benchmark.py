"""Unified benchmark driver with the reference CLI surface.

ref: benchmark/fluid/fluid_benchmark.py (:137 train_parallel) + args.py —
same flags (``--model --device --batch_size --iterations --pass_num
--learning_rate --update_method --use_fake_data --skip_batch_num``), same
model set (mnist, resnet, vgg, se_resnext, stacked_dynamic_lstm,
machine_translation), TPU-native execution:

 - ``--device TPU`` (or GPU, which resolves to whatever accelerator PJRT
   exposes) runs the whole train step as one XLA program;
 - ``--update_method local`` = single-chip Executor;
 - ``--update_method nccl2`` = the pod-SPMD path: the global device mesh
   replaces the NCCL ring (PADDLE_TRAINER_ID / PADDLE_TRAINERS /
   PADDLE_COORDINATOR_ADDR env contract, ref fluid_benchmark.py:34-82);
 - ``--update_method pserver`` is rejected with guidance — async parameter
   serving has no SPMD equivalent by design (SURVEY.md hard part #4;
   transpiler/distribute_transpiler.py documents the redesign).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODELS = ["mnist", "resnet", "vgg", "se_resnext", "stacked_dynamic_lstm",
          "machine_translation", "moe_transformer", "deepfm", "bert"]


def parse_args(argv=None):
    p = argparse.ArgumentParser("fluid_benchmark")
    p.add_argument("--model", choices=MODELS, default="resnet")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=0.001)
    p.add_argument("--skip_batch_num", type=int, default=2,
                   help="warmup batches excluded from timing")
    p.add_argument("--iterations", type=int, default=10)
    p.add_argument("--pass_num", type=int, default=1)
    p.add_argument("--device", choices=["CPU", "GPU", "TPU"], default="TPU")
    p.add_argument("--gpus", type=int, default=1,
                   help="accepted for parity; chips come from the mesh")
    p.add_argument("--data_format", default="NCHW")
    p.add_argument("--use_fake_data", action="store_true", default=True,
                   help="synthetic data (default: no dataset download env)")
    p.add_argument("--use_reader_op", action="store_true")
    p.add_argument("--profile", action="store_true")
    p.add_argument("--transformer_mode", default="dense",
                   choices=["dense", "stacked", "ring"],
                   help="transformer build: dense per-layer graph, "
                        "stacked (pipeline-capable layer-stack op, shards "
                        "over pp/mp meshes), or ring (ring-attention "
                        "sequence parallelism over an sp mesh)")
    p.add_argument("--mesh", default="",
                   help="named mesh axes for SPMD execution, e.g. "
                        "'dp2,pp4' or 'dp2,pp2,mp2' — runs the train step "
                        "through ShardedTrainStep over that mesh (needs "
                        "that many devices; on a dev box set "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N"
                        " with --device CPU)")
    p.add_argument("--update_method", default="local",
                   choices=["local", "pserver", "nccl2"])
    p.add_argument("--no_test", action="store_true")
    return p.parse_args(argv)


def _build(args):
    """Returns (feed_fn, loss, extra) — feed_fn(rng) -> feed dict for one
    batch; extra carries per-model unit info."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    bs, lr = args.batch_size, args.learning_rate
    if args.model == "mnist":
        img, label, pred, loss, acc = models.mnist.mlp()
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
        feed = lambda rng: {
            "img": rng.normal(size=(bs, 784)).astype(np.float32),
            "label": rng.randint(0, 10, size=(bs, 1)).astype(np.int64)}
        return feed, loss, ("mnist", "images/sec", bs)
    if args.model == "resnet":
        hw = 224 if args.device != "CPU" else 64
        cd = 1000 if args.device != "CPU" else 100
        img, label, pred, loss, acc = models.resnet.build(
            class_dim=cd, depth=50, image_shape=(3, hw, hw), lr=lr)
        feed = lambda rng: {
            "img": rng.normal(size=(bs, 3, hw, hw)).astype(np.float32),
            "label": rng.randint(0, cd, size=(bs, 1)).astype(np.int64)}
        return feed, loss, ("resnet50", "images/sec", bs)
    if args.model == "vgg":
        img, label, pred, loss, acc = models.vgg.build(
            class_dim=10, image_shape=(3, 32, 32), lr=lr)
        feed = lambda rng: {
            "img": rng.normal(size=(bs, 3, 32, 32)).astype(np.float32),
            "label": rng.randint(0, 10, size=(bs, 1)).astype(np.int64)}
        return feed, loss, ("vgg16", "images/sec", bs)
    if args.model == "se_resnext":
        hw = 224 if args.device != "CPU" else 64
        cd = 1000 if args.device != "CPU" else 100
        img, label, pred, loss, acc = models.se_resnext.build(
            class_dim=cd, depth=50, image_shape=(3, hw, hw), lr=lr)
        feed = lambda rng: {
            "img": rng.normal(size=(bs, 3, hw, hw)).astype(np.float32),
            "label": rng.randint(0, cd, size=(bs, 1)).astype(np.int64)}
        return feed, loss, ("se_resnext50", "images/sec", bs)
    if args.model == "stacked_dynamic_lstm":
        seq = 64 if args.device != "CPU" else 16
        dict_dim, hid = 5147, (512 if args.device != "CPU" else 64)
        data, label, pred, loss, acc = models.stacked_lstm.build(
            dict_dim=dict_dim, emb_dim=hid, hid_dim=hid, lr=lr)

        def feed(rng):
            lens = [seq] * bs  # fixed bucket: one compiled shape
            total = sum(lens)
            words = fluid.create_lod_tensor(
                rng.randint(0, dict_dim, size=(total, 1)).astype(np.int64),
                [lens], fluid.CPUPlace())
            return {"words": words,
                    "label": rng.randint(0, 2, size=(bs, 1)).astype(np.int64)}
        return feed, loss, ("stacked_dynamic_lstm", "words/sec", bs * seq)
    if args.model == "deepfm":
        # BASELINE config #4: sparse CTR
        fields, vocab = 26, (100000 if args.device != "CPU" else 500)
        feats, label, predict, loss = models.deepfm.build(
            num_fields=fields, vocab_size=vocab,
            embed_dim=16 if args.device != "CPU" else 8, lr=lr)
        feed = lambda rng: {
            "feats": rng.randint(0, vocab,
                                 size=(bs, fields)).astype(np.int64),
            "label": (rng.uniform(size=(bs, 1)) < 0.3).astype(np.float32)}
        return feed, loss, ("deepfm_ctr", "examples/sec", bs)
    if args.model == "bert":
        # BASELINE config #5: BERT-style pretraining
        from paddle_tpu.models import bert as bert_m

        cfg = (bert_m.base_config() if args.device != "CPU"
               else bert_m.tiny_config())
        seq = 128 if args.device != "CPU" else 32
        n_mask = max(1, seq // 8)
        outs = bert_m.build(cfg, seq_len=seq, n_mask=n_mask, lr=lr)
        loss = outs[5]

        def feed(rng):
            return bert_m.synthetic_batch(cfg, bs, seq, n_mask, rng)
        return feed, loss, (f"bert_{cfg.name}", "tokens/sec", bs * seq)
    if args.model in ("machine_translation", "moe_transformer"):
        from paddle_tpu.models import transformer as trf

        seq = 256 if args.device != "CPU" else 32
        cfg = trf.base_config() if args.device != "CPU" else trf.tiny_config()
        if args.model == "moe_transformer":
            # Switch-style MoE FFNs (expert parallelism over an "ep" mesh
            # axis under ParallelExecutor; dense dispatch single-device)
            cfg.name = f"moe_{cfg.name}"
            cfg.moe_experts = 8 if args.device != "CPU" else 4
        if args.transformer_mode == "stacked":
            cfg.stacked = True
            cfg.name = f"{cfg.name}_stacked"
        elif args.transformer_mode == "ring":
            cfg.ring_attention = True
            cfg.name = f"{cfg.name}_ring"
        src, tgt, lbl, loss = trf.build(cfg, src_len=seq, tgt_len=seq, lr=lr)
        feed = lambda rng: {
            "src_word": rng.randint(1, cfg.src_vocab_size,
                                    size=(bs, seq)).astype(np.int64),
            "tgt_word": rng.randint(1, cfg.tgt_vocab_size,
                                    size=(bs, seq)).astype(np.int64),
            "lbl_word": rng.randint(1, cfg.tgt_vocab_size,
                                    size=(bs, seq, 1)).astype(np.int64)}
        return feed, loss, (cfg.name if args.model == "moe_transformer"
                            else "transformer", "tokens/sec", bs * seq)
    raise ValueError(args.model)


def main(argv=None):
    args = parse_args(argv)
    if args.update_method == "pserver":
        print(json.dumps({
            "metric": "pserver_unsupported", "value": 0, "unit": "none",
            "vs_baseline": 0,
            "error": "async parameter serving is replaced by pod-SPMD here; "
                     "use --update_method nccl2 (see "
                     "fluid/transpiler/distribute_transpiler.py)"}))
        return 2

    import jax

    if args.device == "CPU":
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.fluid as fluid

    on_accel = args.device != "CPU"
    if on_accel and os.environ.get("BENCH_AMP", "1") != "0":
        fluid.amp.enable("bfloat16")

    if args.update_method == "nccl2":
        from paddle_tpu.parallel import multihost

        multihost.init()  # PADDLE_* env contract; no-op for 1 process

    feed_fn, loss, (name, unit, items_per_batch) = _build(args)
    place = fluid.TPUPlace() if on_accel else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()

    rng = np.random.RandomState(0)
    feed = feed_fn(rng)

    if args.mesh:
        return _run_mesh(args, fluid, prog, loss, feed, name, unit,
                         items_per_batch)
    if on_accel:
        from paddle_tpu.fluid import core as _core

        dev = _core.get_jax_device(place)
        feed = {k: (jax.device_put(np.asarray(v), dev)
                    if not isinstance(v, fluid.LoDTensor) else v)
                for k, v in feed.items()}

    if args.profile:
        fluid.profiler.start_profiler("All")
    for _ in range(args.skip_batch_num):
        exe.run(prog, feed=feed, fetch_list=[loss])
    t0 = time.perf_counter()
    out = None
    iters = args.iterations * args.pass_num
    for _ in range(iters):
        (out,) = exe.run(prog, feed=feed, fetch_list=[loss],
                         return_numpy=False)
    last = float(np.asarray(out).reshape(-1)[0])
    dt = time.perf_counter() - t0
    if args.profile:
        fluid.profiler.stop_profiler("total", "/tmp/fluid_benchmark_profile")

    rate = items_per_batch * iters / dt
    print(json.dumps({
        "metric": f"{name}_bs{args.batch_size}_{args.device.lower()}"
                  f"_{args.update_method}",
        "value": round(rate, 2), "unit": unit + "/chip",
        "vs_baseline": 0.0, "final_loss": round(last, 4)}))
    return 0


def _run_mesh(args, fluid, prog, loss, feed, name, unit, items_per_batch):
    """--mesh 'dp2,pp4': jit the train step over a named device mesh via
    ShardedTrainStep (the same path dryrun_multichip exercises) — dp
    shards the batch, pp/mp/sp/ep shard the model per the programs'
    dist_spec hints."""
    import re

    from paddle_tpu.parallel.mesh import make_mesh_nd
    from paddle_tpu.parallel.spmd import ShardedTrainStep

    axes = {}
    for part in args.mesh.split(","):
        m = re.fullmatch(r"([a-z]+)(\d+)", part.strip())
        if not m:
            raise SystemExit(f"--mesh: bad axis spec {part!r} "
                             f"(want e.g. dp2,pp4)")
        axes[m.group(1)] = int(m.group(2))
    mesh = make_mesh_nd(**axes)
    step = ShardedTrainStep(prog, list(feed), [loss.name], mesh)
    state = step.place_state()
    placed = step.place_feed({k: np.asarray(v) for k, v in feed.items()})
    for _ in range(max(1, args.skip_batch_num)):  # compile + warmup
        fetches, new_state = step(placed, state)
        state = {**state, **new_state}  # step returns only UPDATED vars

    t0 = time.perf_counter()
    iters = args.iterations * args.pass_num
    for _ in range(iters):
        fetches, new_state = step(placed, state)
        state = {**state, **new_state}
    last = float(np.asarray(fetches[0]).reshape(-1)[0])
    dt = time.perf_counter() - t0
    rate = items_per_batch * iters / dt
    print(json.dumps({
        "metric": f"{name}_bs{args.batch_size}_mesh_{args.mesh}",
        "value": round(rate, 2), "unit": unit + ("" if "/chip" in unit
                                                 else "/global"),
        "vs_baseline": 0.0, "final_loss": round(last, 4),
        "mesh": dict(mesh.shape)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
