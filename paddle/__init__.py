"""Drop-in ``paddle`` namespace: reference scripts run UNCHANGED.

The real package is ``paddle_tpu``; a meta-path finder aliases EVERY
``paddle.X`` import to the already-imported ``paddle_tpu.X`` module
object — the same instance, so module-level state (default programs,
scopes, registries) is shared and ``import paddle.fluid.framework``
can never re-execute the source as a duplicate module.
"""

import importlib
import importlib.abc
import importlib.machinery
import sys as _sys

import paddle_tpu as _impl


class _AliasFinder(importlib.abc.MetaPathFinder, importlib.abc.Loader):
    """paddle.X -> the paddle_tpu.X module instance, for any depth."""

    _prefix = "paddle."

    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith(self._prefix):
            return None
        return importlib.machinery.ModuleSpec(fullname, self,
                                              is_package=True)

    def create_module(self, spec):
        target = "paddle_tpu." + spec.name[len(self._prefix):]
        module = importlib.import_module(target)
        # the import machinery rewrites __spec__/__loader__ on the module
        # it gets back; stash the canonical identity so exec_module can
        # restore it (the alias must not mutate the shared instance)
        spec._alias_identity = (module.__spec__, module.__loader__,
                                module.__package__, module.__name__)
        return module

    def exec_module(self, module):
        spec = module.__spec__
        ident = getattr(spec, "_alias_identity", None)
        if ident is not None:
            (module.__spec__, module.__loader__,
             module.__package__, module.__name__) = ident


_sys.meta_path.insert(0, _AliasFinder())

from paddle_tpu import *  # noqa: E402,F401,F403

# eager attributes for the paths scripts touch without an import statement
fluid = importlib.import_module("paddle.fluid")
v2 = importlib.import_module("paddle.v2")
reader = importlib.import_module("paddle.reader")
dataset = importlib.import_module("paddle.dataset")
trainer_config_helpers = importlib.import_module(
    "paddle.trainer_config_helpers")
batch = _impl.batch

__version__ = _impl.__version__
init = v2.init
infer = v2.infer
