"""Whole-program SPMD smoke (CPU, 8 forced host devices, < 20 s).

The CI oracle for the sharded windowed path (ISSUE 7): a GUARDED 16-step
training window on a dp4×tp2 named mesh — numerics sentinel armed, the
spec table sharding fc weights Megatron-style, mutable state donated —
must complete in at most 2 executor dispatches (startup + one fused
window), train all 16 steps with a finite falling loss, and leave the
topology visible in the mesh-labeled counters plus a non-trivial
``spmd.collective_*`` gauge (GSPMD actually inserted collectives).

Run directly (``python tools/spmd_smoke.py`` — forces the 8-device
virtual CPU mesh itself) or from tier-1 via
``tests/test_spmd_window.py::test_spmd_smoke_tool`` (conftest already
forces it).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_concurrency_optimized_scheduler" not in _flags:
    _flags += " --xla_cpu_enable_concurrency_optimized_scheduler=false"
os.environ["XLA_FLAGS"] = _flags
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 16
MESH = "dp4,tp2"


def main() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import observe
    from paddle_tpu.fluid import guardian
    from paddle_tpu.fluid.parallel_executor import ParallelExecutor

    t0 = time.perf_counter()
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
            loss, startup_program=startup)

    rng = np.random.RandomState(3)
    feed = {  # one (N_STEPS, batch, ...) window; batch 8 divides dp4
        "x": rng.normal(size=(N_STEPS, 8, 16)).astype(np.float32),
        "y": rng.randint(0, 10, size=(N_STEPS, 8, 1)).astype(np.int64)}

    scope = fluid.Scope()
    guardian.install(guardian.GuardianConfig(policy="skip"))
    counters0 = dict(fluid.profiler.counters())
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            pe = ParallelExecutor(loss_name=loss.name, main_program=prog,
                                  mesh=MESH)
            (first,) = pe.run_steps([loss], feed=feed, n_steps=N_STEPS,
                                    feed_per_step=True)
            guardian.flush()
            gm = guardian.metrics()
    finally:
        guardian.disable()

    c = fluid.profiler.counters()

    def delta(name):
        return c.get(name, 0) - counters0.get(name, 0)

    dispatches = delta("executor.dispatches")
    label = pe.mesh_label
    coll = c.get('spmd.collective_bytes{mesh="%s"}' % label, 0)
    last = float(np.asarray(first).reshape(-1)[0])
    report = {
        "ok": bool(
            dispatches <= 2
            and delta("executor.windows") == 1
            and delta("executor.window_steps") == N_STEPS
            and delta('executor.dispatches{mesh="%s"}' % label) == 1
            and gm.get("steps") == N_STEPS
            and gm.get("trips", 0) == 0
            and coll > 0
            and np.isfinite(last)),
        "mesh": label,
        "dispatches": int(dispatches),
        "windows": int(delta("executor.windows")),
        "window_steps": int(delta("executor.window_steps")),
        "dispatches_per_step": round(1.0 / N_STEPS, 4),
        "guardian_steps": gm.get("steps"),
        "collective_bytes": int(coll),
        "last_loss": last,
        "mesh_observed": observe.current_mesh(),
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
