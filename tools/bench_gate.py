"""Bench regression gate: newest BENCH_*.json vs the previous round.

The BENCH trajectory (BENCH_r01.json, BENCH_r02.json, ...) records each
round's headline throughputs; this tool diffs the two newest rounds and
exits non-zero when any shared metric regressed by more than
``--threshold`` percent.  TIER-1 (ISSUE 11, ROADMAP item 2):
``tests/test_bench_gate.py`` runs it as a blocking test — 30% at first
(just above the committed r04→r05 -26.65% ResNet noise band), ratcheted
to 20% once the fused-kernel layer landed (ISSUE 12) and the newest
rounds stabilized inside the tighter band — so a flat-regression round
fails a PR instead of landing silently.  Tighter thresholds remain
available for pre-merge hooks and by-hand runs.  Every BENCH line since
ISSUE 12 also records the active kernel config (``flash``/``fused``), so
a gate trip is attributable to the kernel change that caused it.

Metric extraction: every line of a round's ``tail`` that parses as JSON
with ``metric``/``value`` keys contributes (the per-model lines AND the
combined final line; later lines win on duplicate metric names), plus
the ``parsed`` dict as a fallback for single-line rounds.  Error lines
(``value == 0`` with an ``error`` field) are skipped on BOTH sides, so a
model that crashed in one round neither gates nor masks.

Usage::

    python tools/bench_gate.py [--dir .] [--threshold 25] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def find_rounds(dir_path: str):
    """[(round_number, path)] sorted ascending."""
    out = []
    for path in glob.glob(os.path.join(dir_path, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def extract_metrics(path: str) -> dict:
    """{metric_name: value} from one BENCH round file."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    metrics = {}

    def _take(rec):
        if not isinstance(rec, dict):
            return
        name, value = rec.get("metric"), rec.get("value")
        if not name or not isinstance(value, (int, float)):
            return
        if rec.get("error") or value <= 0:
            return  # crashed/degenerate lines neither gate nor mask
        metrics[name] = float(value)
        # the combined final line carries the transformer number inline
        tm, tv = rec.get("transformer_metric"), \
            rec.get("transformer_tokens_per_sec_chip")
        if tm and isinstance(tv, (int, float)) and tv > 0:
            metrics[tm] = float(tv)

    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            _take(json.loads(line))
        except ValueError:
            continue
    _take(doc.get("parsed"))
    return metrics


def compare(prev: dict, cur: dict, threshold_pct: float) -> dict:
    """Diff two metric dicts; a regression is a drop > threshold_pct."""
    rows = []
    regressions = []
    for name in sorted(set(prev) & set(cur)):
        p, c = prev[name], cur[name]
        change_pct = (c - p) / p * 100.0 if p else 0.0
        row = {"metric": name, "prev": p, "cur": c,
               "change_pct": round(change_pct, 2)}
        rows.append(row)
        if change_pct < -threshold_pct:
            regressions.append(row)
    return {"compared": rows, "regressions": regressions,
            "only_prev": sorted(set(prev) - set(cur)),
            "only_cur": sorted(set(cur) - set(prev))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate on BENCH_*.json regressions (newest vs "
                    "previous round).")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="where the BENCH files live")
    ap.add_argument("--threshold", type=float, default=25.0,
                    help="max tolerated drop, percent (default 25)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report only")
    args = ap.parse_args(argv)

    rounds = find_rounds(args.dir)
    if len(rounds) < 2:
        print(json.dumps({"ok": True, "skipped": True,
                          "note": f"need 2+ BENCH rounds under "
                                  f"{args.dir}, found {len(rounds)}"}))
        return 0
    (n_prev, p_prev), (n_cur, p_cur) = rounds[-2], rounds[-1]
    prev, cur = extract_metrics(p_prev), extract_metrics(p_cur)
    result = compare(prev, cur, args.threshold)
    ok = not result["regressions"]
    report = {"ok": ok, "prev_round": n_prev, "cur_round": n_cur,
              "threshold_pct": args.threshold, **result}
    if args.json:
        print(json.dumps(report))
    else:
        print(json.dumps(report, indent=1))
        for r in result["regressions"]:
            print(f"REGRESSION {r['metric']}: {r['prev']} -> {r['cur']} "
                  f"({r['change_pct']}%)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
