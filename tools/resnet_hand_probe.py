"""Hand-JAX vs framework ResNet-50 train step (round-5 MFU isolation #2).

The train-step structure probe cleared BN/backward/momentum (all sustain
130-175 TFLOPs on the tunnel), so the 21.5-TFLOP full step must lose its
6x either to the REAL ResNet-50 geometry (224px stem, strides, 1x1
bottlenecks, small-channel early stages) or to the framework's lowered
program (extra casts/copies, layout, non-donated buffers).  This probe
separates the two by timing, identically:

  hand        a pure-JAX ResNet-50 bottleneck train step written directly
              (NCHW, bf16 convs w/ fp32 master params, train-mode BN,
              momentum SGD, softmax CE) — the best this geometry can do
  framework   the fluid-built program through Executor.run with AMP, the
              exact bench path

Same batch/shape/steps/timing discipline (async dispatches, block on the
last loss).  TFLOPs use the bench's accounting (3 x 3.86 GFLOP/img).
XLA's own cost_analysis FLOP count is reported for the hand step so the
accounting can be cross-checked against what the compiler thinks.

Usage: python tools/resnet_hand_probe.py [BATCH STEPS]
PROBE_PLATFORM=cpu for smoke runs (tiny shapes).
PROBE_VARIANT=hand|framework|both (default both) — run one side only so a
short tunnel alive-window still captures something.
PROBE_SINK=path.jsonl — also append emitted lines there (survives kills).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax

if os.environ.get("PROBE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])

import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = os.environ.get("PROBE_PLATFORM") == "cpu"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else (4 if SMOKE else 256)
STEPS = int(sys.argv[2]) if len(sys.argv) > 2 else (2 if SMOKE else 12)
HW = 64 if SMOKE else 224
CLASSES = 100 if SMOKE else 1000
DN = ("NCHW", "OIHW", "NCHW")
BLOCKS = [3, 4, 6, 3]


def emit(**kw):
    line = json.dumps(kw)
    print(line, flush=True)
    sink = os.environ.get("PROBE_SINK")
    if sink:
        try:
            with open(sink, "a") as f:
                f.write(line + "\n")
        except OSError as e:
            print(f"# PROBE_SINK write failed: {e}", flush=True)


def note(msg):
    print(f"# {msg} [{time.strftime('%H:%M:%S')}]", flush=True)


# ---------------- hand-written ResNet-50 ----------------

def conv(x, w, stride):
    return lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride), "SAME",
        dimension_numbers=DN)


def bn_relu(x, p, relu=True):
    xf = jnp.float32(x)
    mean = xf.mean(axis=(0, 2, 3), keepdims=True)
    var = xf.var(axis=(0, 2, 3), keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + 1e-5)
    y = y * p["gamma"][None, :, None, None] + p["beta"][None, :, None, None]
    y = y.astype(jnp.bfloat16)
    return jax.nn.relu(y) if relu else y


def make_conv_bn(key, cin, cout, k):
    kw, key = jax.random.split(key)
    fan = cin * k * k
    return {
        "w": jax.random.normal(kw, (cout, cin, k, k), jnp.float32)
        * np.sqrt(2.0 / fan),
        "gamma": jnp.ones((cout,), jnp.float32),
        "beta": jnp.zeros((cout,), jnp.float32),
    }, key


def make_params(key):
    params = {}
    params["stem"], key = make_conv_bn(key, 3, 64, 7)
    cin = 64
    for si, (n, width) in enumerate(zip(BLOCKS, [64, 128, 256, 512])):
        for bi in range(n):
            blk = {}
            blk["c1"], key = make_conv_bn(key, cin, width, 1)
            blk["c2"], key = make_conv_bn(key, width, width, 3)
            blk["c3"], key = make_conv_bn(key, width, width * 4, 1)
            if bi == 0:
                blk["sc"], key = make_conv_bn(key, cin, width * 4, 1)
            params[f"s{si}b{bi}"] = blk
            cin = width * 4
    kfc, key = jax.random.split(key)
    params["fc_w"] = jax.random.normal(
        kfc, (2048, CLASSES), jnp.float32) * 0.01
    params["fc_b"] = jnp.zeros((CLASSES,), jnp.float32)
    return params


def forward(params, img):
    x = conv(img.astype(jnp.bfloat16), params["stem"]["w"], 2)
    x = bn_relu(x, params["stem"])
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
                          "SAME")
    for si, (n, width) in enumerate(zip(BLOCKS, [64, 128, 256, 512])):
        for bi in range(n):
            blk = params[f"s{si}b{bi}"]
            stride = 2 if (bi == 0 and si > 0) else 1
            short = x
            if "sc" in blk:
                short = bn_relu(conv(x, blk["sc"]["w"], stride), blk["sc"],
                                relu=False)
            y = bn_relu(conv(x, blk["c1"]["w"], stride), blk["c1"])
            y = bn_relu(conv(y, blk["c2"]["w"], 1), blk["c2"])
            y = bn_relu(conv(y, blk["c3"]["w"], 1), blk["c3"], relu=False)
            x = jax.nn.relu(short + y)
    x = jnp.float32(x).mean(axis=(2, 3))  # global avg pool
    return x @ params["fc_w"] + params["fc_b"]


def loss_fn(params, img, label):
    logits = forward(params, img)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, label, axis=1).mean()


def train_step(params, vel, img, label):
    loss, grads = jax.value_and_grad(loss_fn)(params, img, label)
    vel = jax.tree.map(lambda v, g: 0.9 * v + g, vel, grads)
    params = jax.tree.map(lambda p, v: p - 0.1 * v, params, vel)
    return loss, params, vel


def timed(step, n):
    out = None
    t0 = time.perf_counter()
    for _ in range(n):
        out = step()
    loss = float(np.asarray(out[0] if isinstance(out, tuple) else out)
                 .reshape(-1)[0])
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), loss
    return dt


def run_hand_variant(img, label, tflop_step):
    note("hand: building params")
    params = make_params(jax.random.PRNGKey(0))
    vel = jax.tree.map(jnp.zeros_like, params)
    step = jax.jit(train_step, donate_argnums=(0, 1))
    note("hand: lowering + compiling (full ResNet-50 — can take minutes "
         "over the tunnel)")
    t0 = time.time()
    lowered = step.lower(params, vel, img, label)
    compiled = lowered.compile()
    compile_s = time.time() - t0
    note(f"hand: compiled in {compile_s:.1f}s; warming")
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        xla_flops = float(ca.get("flops", 0.0))
    except Exception:
        xla_flops = 0.0

    state = {"p": params, "v": vel}

    def run_hand():
        loss, state["p"], state["v"] = compiled(state["p"], state["v"],
                                                img, label)
        return loss

    run_hand()  # warm
    note("hand: timing")
    dt = timed(run_hand, STEPS)
    emit(variant="hand_jax", ms_per_step=round(dt / STEPS * 1e3, 2),
         tflops=round(tflop_step * STEPS / dt, 1),
         imgs_per_sec=round(BATCH * STEPS / dt, 1),
         xla_counted_tflop_per_step=round(xla_flops / 1e12, 3),
         compile_s=round(compile_s, 1),
         device=jax.devices()[0].platform)


def run_framework_variant(img, label, tflop_step):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import resnet

    if not SMOKE:
        # Match the bench regime exactly: keep-low activations defaults ON
        # there (BENCH_AMP_KEEP/PADDLE_TPU_AMP_KEEP default "1").
        keep = os.environ.get("PADDLE_TPU_AMP_KEEP", "1").strip().lower() \
            not in ("0", "false")
        fluid.amp.enable("bfloat16", keep_activations=keep)
    note("framework: building program")
    _, _, _, loss, _ = resnet.build(
        class_dim=CLASSES, depth=50, image_shape=(3, HW, HW), lr=0.1)
    place = fluid.CPUPlace() if SMOKE else fluid.TPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())
    prog = fluid.default_main_program()
    feed = {"img": np.asarray(img), "label": np.asarray(label)}
    if not SMOKE:
        from paddle_tpu.fluid import core as _core
        dev = _core.get_jax_device(place)
        feed = {k: jax.device_put(v, dev) for k, v in feed.items()}

    def run_fw():
        (out,) = exe.run(prog, feed=feed, fetch_list=[loss],
                         return_numpy=False)
        return out

    note("framework: tracing + compiling (first run)")
    t0 = time.time()
    run_fw()
    fw_compile_s = time.time() - t0
    note(f"framework: first run in {fw_compile_s:.1f}s; timing")
    run_fw()
    dt = timed(run_fw, STEPS)
    emit(variant="framework", ms_per_step=round(dt / STEPS * 1e3, 2),
         tflops=round(tflop_step * STEPS / dt, 1),
         imgs_per_sec=round(BATCH * STEPS / dt, 1),
         first_run_s=round(fw_compile_s, 1),
         amp=fluid.amp.compute_dtype() or "off")


def main():
    which = os.environ.get("PROBE_VARIANT", "both")
    if which not in ("hand", "framework", "both"):
        raise SystemExit(f"PROBE_VARIANT must be hand|framework|both, "
                         f"got {which!r}")

    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.normal(size=(BATCH, 3, HW, HW)).astype(np.float32))
    label = jnp.asarray(rng.randint(0, CLASSES, size=(BATCH, 1)))
    gflop_img = 3 * 3.86 * (HW / 224.0) ** 2  # bench accounting
    tflop_step = gflop_img * BATCH / 1e3

    if which in ("hand", "both"):
        run_hand_variant(img, label, tflop_step)
    if which in ("framework", "both"):
        run_framework_variant(img, label, tflop_step)


if __name__ == "__main__":
    main()
