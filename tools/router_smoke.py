#!/usr/bin/env python
"""Serving-fleet smoke (CPU, < 10 s) — the ISSUE 17 CI oracle.

Two models x two replicas behind one router, end to end through the
fleet lifecycle:

 1. all four replicas warm from ONE shared compile store: only the
    first replica of the architecture actually compiles; every other
    cold start is cache-hit-only;
 2. a replica is killed MID-LOAD by the deterministic fault hook
    (``PADDLE_FAULT_REPLICA_KILL_AFTER``): its in-flight requests fail
    over through the router to the survivor with zero shed and bitwise
    the same outputs, and the census re-spawns a replacement whose
    re-warm dispatches NOTHING (``warmup_dispatches == 0``);
 3. a load spike overflows the router's hard queue bound: the scale
    policy's last-chance hook fires an emergency ``fleet.scale_out``
    strictly before any shed — the spike completes with shed == 0 and
    a third replica serving.

Run directly (``python tools/router_smoke.py``) or from tier-1 via
``tests/test_router.py::test_router_smoke_tool_runs_clean``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _wait(pred, timeout_s=30.0, tick=None):
    deadline = time.perf_counter() + timeout_s
    while not pred():
        if time.perf_counter() > deadline:
            return False
        if tick is not None:
            tick()
        time.sleep(0.01)
    return True


def main() -> dict:
    # the shared compile store is the POINT of the fleet's warm path:
    # replicas 2..N and every respawn must come up cache-hit-only
    if not os.environ.get("PADDLE_COMPILE_CACHE_DIR"):
        os.environ["PADDLE_COMPILE_CACHE_DIR"] = \
            tempfile.mkdtemp(prefix="router_smoke_cache_")

    import numpy as np

    from paddle_tpu import observe
    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (AutoscalePolicy, DecodeEngine,
                                    RouterConfig, ServingFleet)
    from paddle_tpu.observe.fleet import fleet_events

    t_start = time.perf_counter()
    report = {"ok": False}
    fleet = None
    obs_root = tempfile.mkdtemp(prefix="router_smoke_obs_")
    observe.configure(obs_root)

    def events(name):
        observe.get_sink().flush()
        return [r for r in fleet_events(obs_root)
                if r.get("event") == name]

    def factory(seed):
        def make(labels):
            model = transformer.DecodeModel(
                cfg=transformer.decode_lm_config(), max_slots=2,
                max_len=32, prefill_buckets=[4], seed=seed)
            return DecodeEngine(model, metrics_labels=labels)
        return make

    try:
        fleet = ServingFleet(
            {"chat": factory(5), "code": factory(9)},
            replicas=2,
            hb_dir=tempfile.mkdtemp(prefix="router_smoke_hb_"),
            # min_replicas=2 + a long cooldown pin the baseline fleet
            # shape; eval_s=30 idles the monitor so the smoke drives
            # poll_once() deterministically
            policy=AutoscalePolicy(min_replicas=2, max_replicas=3,
                                   cooldown_s=60.0, queue_high=6,
                                   hysteresis_ticks=2),
            router_config=RouterConfig(queue_hard=16),
            eval_s=30.0)

        # -- 1. four replicas, one compile --------------------------------
        fleet.start(wait_ready_s=90.0)
        ok_ready = _wait(lambda: all(
            fleet.status()["models"][m]["ready"] == 2
            for m in ("chat", "code")), timeout_s=60.0)
        report["all_ready"] = ok_ready
        report["warm_s"] = round(time.perf_counter() - t_start, 2)
        ready_events = events("fleet.replica_ready")
        report["initial_replicas"] = len(ready_events)
        report["cold_compiles"] = sum(
            1 for e in ready_events if e.get("warmup_dispatches", 0) > 0)
        report["cached_warms"] = sum(
            1 for e in ready_events
            if e.get("warmup_dispatches") == 0
            and e.get("warmup_cached", 0) > 0)

        rng = np.random.RandomState(7)
        prompts = [[int(t) for t in rng.randint(2, 60, size=3)]
                   for _ in range(4)]
        base = {m: [fleet.generate(m, p, 6) for p in prompts]
                for m in ("chat", "code")}
        report["models_disagree"] = base["chat"] != base["code"]

        # -- 2. kill one replica mid-load: zero-shed failover -------------
        served_now = max(r["served"] for r in
                         fleet.status()["models"]["chat"]["replicas"])
        _fault.install(_fault.FaultPlan(
            replica_kill_after=served_now + 2))
        try:
            futs = [fleet.submit("chat", prompts[i % 4], 6)
                    for i in range(10)]
            got = [f.result(timeout=60) for f in futs]
        finally:
            _fault.clear()
        report["failover_bitwise"] = all(
            got[i] == base["chat"][i % 4] for i in range(10))
        dead = events("fleet.replica_dead")
        report["killed"] = [e["replica"] for e in dead
                            if e.get("reason") == "fault_injected"]

        # census: account the death, re-spawn on a surviving device
        _wait(lambda: fleet.status()["models"]["chat"]["ready"] >= 2,
              timeout_s=60.0, tick=fleet.poll_once)
        respawns = events("fleet.respawn")
        report["respawned"] = [e["replica"] for e in respawns]
        new_names = {e["replica"] for e in respawns}
        rewarm = [e for e in events("fleet.replica_ready")
                  if e["replica"] in new_names]
        report["rewarm_dispatches"] = \
            [e.get("warmup_dispatches") for e in rewarm]
        report["rewarm_cached"] = [e.get("warmup_cached") for e in rewarm]
        report["post_respawn_bitwise"] = \
            [fleet.generate("chat", p, 6) for p in prompts] \
            == base["chat"]

        # -- 3. load spike: scale-out strictly before any shed ------------
        primers = [fleet.submit("code", prompts[i % 4], 12)
                   for i in range(4)]  # occupy every code slot
        spike = [fleet.submit("code", prompts[i % 4], 4)
                 for i in range(64)]
        spike_ok = sum(1 for f in spike
                       if f.result(timeout=120) is not None)
        for f in primers:
            f.result(timeout=120)
        report["spike_completed"] = spike_ok
        scale_outs = [e for e in events("fleet.scale_out")
                      if e.get("model") == "code"]
        report["scale_out_reasons"] = \
            [e.get("reason") for e in scale_outs]
        report["shed_events"] = len(events("fleet.shed"))
        status = fleet.status()
        report["shed"] = {m: status["models"][m]["shed"]
                          for m in ("chat", "code")}
        report["code_replicas_ready"] = _wait(
            lambda: fleet.status()["models"]["code"]["ready"] >= 3,
            timeout_s=60.0)

        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["all_ready"]
            and report["initial_replicas"] >= 4
            and report["cold_compiles"] <= 1
            and report["cached_warms"] >= 3
            and report["models_disagree"]
            and report["failover_bitwise"]
            and len(report["killed"]) == 1
            and len(report["respawned"]) == 1
            and report["rewarm_dispatches"] == [0]
            and all(c > 0 for c in report["rewarm_cached"])
            and report["post_respawn_bitwise"]
            and report["spike_completed"] == 64
            and len(scale_outs) >= 1
            and report["shed_events"] == 0
            and report["shed"] == {"chat": 0, "code": 0}
            and report["code_replicas_ready"])
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        _fault.clear()
        if fleet is not None:
            try:
                fleet.shutdown(timeout_s=15)
            except Exception:
                pass
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
