#!/usr/bin/env python
"""Continuous-batching decode smoke (CPU, < 10 s) — the ISSUE 15 CI oracle.

A mixed-length workload through the DecodeEngine: one LONG generation
(32 tokens) submitted FIRST, then three short ones (6 tokens each).
Under request-granularity batching the shorts would convoy behind the
long request; iteration-level scheduling must retire them early:

 - every short request completes strictly BEFORE the long one;
 - the compile counter stays FLAT across all traffic after warmup()
   (the fixed-executable-set invariant: one decode step + the prefill
   buckets, nothing else);
 - generated tokens are bitwise identical to per-request sequential
   decode of the same prompts (``decode_static`` one at a time);
 - TTFT and inter-token latency series are populated.

Run directly (``python tools/decode_smoke.py``) or from tier-1 via
``tests/test_decode_engine.py::test_decode_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LONG_NEW = 32
SHORT_NEW = 6
N_SHORT = 3


def main() -> dict:
    import numpy as np

    from paddle_tpu.models import transformer
    from paddle_tpu.serving import DecodeEngine

    t_start = time.perf_counter()
    report = {"ok": False}
    eng = None
    try:
        model = transformer.DecodeModel(cfg=transformer.decode_lm_config(),
                                        max_slots=4, max_len=64,
                                        prefill_buckets=[4, 8])
        eng = DecodeEngine(model)
        report["executables_after_warmup"] = eng.warmup()
        compiles0 = eng.metrics.snapshot()["bucket_compiles"]

        rng = np.random.RandomState(7)
        prompts = [[int(t) for t in rng.randint(2, model.vocab_size - 1,
                                                size=3)]
                   for _ in range(1 + N_SHORT)]
        jobs = [(prompts[0], LONG_NEW)] + \
               [(p, SHORT_NEW) for p in prompts[1:]]

        # sequential per-request baseline (same executables)
        sequential = [eng.decode_static([j])[0][0] for j in jobs]

        done_at = {}

        def stamp(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
            return cb

        futs = []
        for i, (p, n) in enumerate(jobs):
            f = eng.submit(p, n)
            f.add_done_callback(stamp(i))
            futs.append(f)
        outs = [f.result(timeout=60) for f in futs]

        report["long_tokens"] = len(outs[0])
        report["short_tokens"] = [len(o) for o in outs[1:]]
        report["shorts_before_long"] = all(
            done_at[i] < done_at[0] for i in range(1, len(jobs)))
        report["bitwise_sequential"] = outs == sequential
        snap = eng.metrics.snapshot()
        report["compiles_after_warmup"] = \
            snap["bucket_compiles"] - compiles0
        report["decode_ticks"] = snap["decode_ticks"]
        report["ttft_p50_ms"] = snap["ttft_p50_ms"]
        report["intertoken_p50_ms"] = snap["intertoken_p50_ms"]
        report["slots_free"] = snap.get("slots_free")
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["shorts_before_long"]
            and report["bitwise_sequential"]
            and report["compiles_after_warmup"] == 0
            and snap["completed"] == len(jobs)
            and report["ttft_p50_ms"] is not None
            and report["intertoken_p50_ms"] is not None)
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        if eng is not None:
            try:
                eng.shutdown(timeout_s=10)
            except Exception:
                pass
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
