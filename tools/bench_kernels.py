"""Per-kernel microbench: fused Pallas lowerings vs their XLA references.

BENCH_r*.json tracks whole-model throughput; this tool times each fused
kernel FAMILY in isolation (forward + backward where it exists) against
the unfused XLA lowering it replaces, so a BENCH trajectory move is
attributable to a specific kernel ("the layer that finally moves
vs_baseline" — ISSUE 12).  One JSON line per kernel::

    {"kernel": "softmax_xent", "shape": [4096, 32000],
     "fused_ms": 1.91, "unfused_ms": 3.42, "speedup": 1.79,
     "max_err": 2.4e-07, "backend": "tpu"}

On a CPU backend the Pallas kernels run in INTERPRET mode — a correctness
tool, not a fast path — so ``speedup < 1`` there is expected and the
numbers matter only on a real TPU VM.  ``--smoke`` shrinks every shape
and asserts parity (max_err) instead of judging speed; tier-1 runs it via
``tests/test_pallas_fused.py::test_bench_kernels_smoke``.

Usage::

    python tools/bench_kernels.py [--smoke] [--steps N] [--kernel NAME]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, args, steps):
    fn(*args)  # compile
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        out = fn(*args)
    import jax

    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps * 1e3


def _err(a, b):
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    import numpy as np

    return float(max(np.abs(np.asarray(x, np.float32)
                            - np.asarray(y, np.float32)).max()
                     for x, y in zip(la, lb)))


def bench_softmax_xent(smoke, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops import pallas_fused as pf

    r, v = (64, 512) if smoke else (4096, 32000)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(r, v)).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, v, size=(r, 1)).astype(np.int32))

    def fused(x):
        loss, _ = pf.softmax_xent(x, lab)
        return jnp.sum(loss)

    def unfused(x):
        logp = jax.nn.log_softmax(x, axis=-1)
        return jnp.sum(-jnp.take_along_axis(logp, lab.astype(jnp.int64), 1))

    f_g = jax.jit(jax.value_and_grad(fused))
    u_g = jax.jit(jax.value_and_grad(unfused))
    return {"kernel": "softmax_xent", "shape": [r, v],
            "fused_ms": round(_timeit(f_g, (x,), steps), 3),
            "unfused_ms": round(_timeit(u_g, (x,), steps), 3),
            "max_err": _err(f_g(x), u_g(x))}


def bench_flash_attention(smoke, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas_flash import flash_attention
    from paddle_tpu.parallel.ring_attention import full_attention

    b, h, t, d = (1, 2, 64, 16) if smoke else (4, 8, 1024, 64)
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, t, d)).astype(np.float32))
               for _ in range(3))
    bq = bk = 32 if smoke else 256

    f = jax.jit(jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, block_q=bq, block_k=bk) ** 2),
        argnums=(0, 1, 2)))
    u = jax.jit(jax.grad(lambda q, k, v: jnp.sum(full_attention(
        q, k, v, True) ** 2), argnums=(0, 1, 2)))
    return {"kernel": "flash_attention", "shape": [b, h, t, d],
            "fused_ms": round(_timeit(f, (q, k, v), steps), 3),
            "unfused_ms": round(_timeit(u, (q, k, v), steps), 3),
            "max_err": _err(f(q, k, v), u(q, k, v))}


def _bench_opt(name, smoke, steps):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops import pallas_fused as pf

    n = (8 * 1024) if smoke else (16 * 1024 * 1024)
    rng = np.random.RandomState(2)
    p, g, a1, a2 = (jnp.asarray(rng.normal(size=(n // 128, 128))
                                .astype(np.float32)) for _ in range(4))
    a2 = jnp.abs(a2)
    lr = jnp.float32(0.01)

    if name == "adam":
        f = jax.jit(lambda p, g, a1, a2: pf.fused_adam(
            p, g, a1, a2, lr, 0.9, 0.999, 1e-8))

        def u(p, g, a1, a2):
            m1o = 0.9 * a1 + 0.1 * g
            m2o = 0.999 * a2 + 0.001 * g * g
            return p - lr * m1o / (jnp.sqrt(m2o) + 1e-8), m1o, m2o

        u = jax.jit(u)
        args = (p, g, a1, a2)
    else:
        f = jax.jit(lambda p, g, a1: pf.fused_momentum(
            p, g, a1, lr, 0.9, False))

        def u(p, g, a1):
            vo = 0.9 * a1 + g
            return p - lr * vo, vo

        u = jax.jit(u)
        args = (p, g, a1)
    return {"kernel": name, "shape": [n],
            "fused_ms": round(_timeit(f, args, steps), 3),
            "unfused_ms": round(_timeit(u, args, steps), 3),
            "max_err": _err(f(*args), u(*args))}


KERNELS = {
    "softmax_xent": bench_softmax_xent,
    "flash_attention": bench_flash_attention,
    "adam": lambda s, n: _bench_opt("adam", s, n),
    "momentum": lambda s, n: _bench_opt("momentum", s, n),
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes; assert parity, ignore speed")
    ap.add_argument("--steps", type=int, default=None,
                    help="timed iterations per kernel")
    ap.add_argument("--kernel", choices=sorted(KERNELS),
                    help="bench one kernel family only")
    args = ap.parse_args(argv)

    import jax

    backend = jax.default_backend()
    steps = args.steps or (3 if args.smoke else 20)
    ok = True
    for name in ([args.kernel] if args.kernel else sorted(KERNELS)):
        try:
            row = KERNELS[name](args.smoke, steps)
            row["backend"] = backend
            row["interpret"] = backend != "tpu"
            if row["fused_ms"] > 0:
                row["speedup"] = round(row["unfused_ms"] / row["fused_ms"], 2)
            if row["max_err"] > 1e-3:
                row["error"] = f"parity failure: max_err {row['max_err']}"
                ok = False
        except Exception as exc:  # a failing kernel must not mask others
            row = {"kernel": name, "error": f"{type(exc).__name__}: {exc}",
                   "backend": backend}
            ok = False
        print(json.dumps(row), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
