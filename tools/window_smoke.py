"""Windowed-training smoke (CPU, < 5 s).

The CI oracle for the device-resident training window (ISSUE 6): a
GUARDED 16-step training window — numerics sentinel armed, batches staged
through a DevicePrefetcher — must complete in at most 2 executor
dispatches (startup + one fused window; the whole point of the window is
that 16 steps are NOT 16 dispatches), train all 16 steps, and leave the
window visible in the always-on counters (``executor.windows`` /
``executor.window_steps``).

Run directly (``python tools/window_smoke.py``) or from tier-1 via
``tests/test_prefetch.py::test_window_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 16


def main() -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import guardian
    from paddle_tpu.fluid.prefetch import DevicePrefetcher

    t0 = time.perf_counter()
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)

    rng = np.random.RandomState(3)

    def batches():
        for _ in range(N_STEPS):
            yield {"x": rng.normal(size=(8, 8)).astype(np.float32),
                   "y": rng.normal(size=(8, 1)).astype(np.float32)}

    scope = fluid.Scope()
    guardian.install(guardian.GuardianConfig(policy="skip"))
    counters0 = dict(fluid.profiler.counters())
    losses = []
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            with DevicePrefetcher(batches(), n_steps=N_STEPS,
                                  place=fluid.CPUPlace(), depth=2) as pf:
                for feed_dev, count in pf:
                    (lv,) = exe.run_steps(prog, feed=feed_dev,
                                          fetch_list=[loss], n_steps=count,
                                          feed_per_step=True)
                    losses.append(float(np.asarray(lv).reshape(-1)[0]))
            guardian.flush()
            gm = guardian.metrics()
    finally:
        guardian.disable()

    c = fluid.profiler.counters()

    def delta(name):
        return c.get(name, 0) - counters0.get(name, 0)

    dispatches = delta("executor.dispatches")
    report = {
        "ok": bool(
            dispatches <= 2
            and delta("executor.windows") == 1
            and delta("executor.window_steps") == N_STEPS
            and gm.get("steps") == N_STEPS
            and gm.get("trips", 0) == 0
            and losses and np.isfinite(losses[-1])),
        "dispatches": int(dispatches),
        "windows": int(delta("executor.windows")),
        "window_steps": int(delta("executor.window_steps")),
        "guardian_steps": gm.get("steps"),
        "last_loss": losses[-1] if losses else None,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
