#!/usr/bin/env python
"""Tier-1 smoke for the pre-compile program verifier (<2s after import).

 1. builds an MLP training program and a tiny dp2,tp2-meshed transformer;
 2. strict-verifies both — must be CLEAN (no errors, no warnings);
 3. seeds a shape bug (fc weight resized) — must be caught as AN101;
 4. round-trips the ``python -m paddle_tpu.analysis lint`` CLI surface;
 5. measures verify latency — p50 must be under 50ms per program.

Prints one BENCH-style JSON line; exit 0 = all gates pass.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_mlp():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework

    framework.fresh_session()
    img = fluid.layers.data(name="img", shape=[16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=img, size=32, act="relu")
    pred = fluid.layers.fc(input=h, size=10, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=pred, label=label))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    return fluid.default_main_program(), ["img", "label"], [loss]


def build_transformer():
    from paddle_tpu.fluid import framework
    from paddle_tpu.models import transformer

    framework.fresh_session()
    src, tgt, lbl, cost = transformer.build(transformer.tiny_config(),
                                            src_len=8, tgt_len=8)
    import paddle_tpu.fluid as fluid

    return fluid.default_main_program(), [src.name, tgt.name, lbl.name], \
        [cost]


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from paddle_tpu import analysis

    results = {"tool": "verify_smoke"}
    failures = []

    # 1+2: both reference programs strict-clean
    mlp_prog, mlp_feed, mlp_fetch = build_mlp()
    mlp_feed_arrays = {"img": np.zeros((8, 16), np.float32),
                      "label": np.zeros((8, 1), np.int64)}
    tr_prog, tr_feed, tr_fetch = build_transformer()

    durations = []
    for _ in range(5):
        r_mlp = analysis.verify_program(mlp_prog, feed=mlp_feed_arrays,
                                        fetch_list=mlp_fetch,
                                        kind="run_steps")
        durations.append(r_mlp.duration_ms)
        r_tr = analysis.verify_program(tr_prog, feed=tr_feed,
                                       fetch_list=tr_fetch,
                                       mesh="dp2,tp2", kind="pe_run_steps")
        durations.append(r_tr.duration_ms)
    if not r_mlp.clean:
        failures.append("mlp not clean: " + r_mlp.format("warn"))
    if not r_tr.clean:
        failures.append("transformer not clean: " + r_tr.format("warn"))
    results["mesh"] = r_tr.mesh
    results["collective_bytes_est"] = r_tr.collective_bytes_est
    if not (r_tr.collective_bytes_est or 0) > 0:
        failures.append("dp2,tp2 transformer produced no collective "
                        "estimate")

    # 3: seeded shape bug caught with a named code
    gb = mlp_prog.global_block()
    weight = next(v for v in gb.vars.values()
                  if v.shape == (16, 32))
    weight.shape = (16, 31)
    mlp_prog._bump_version()
    r_bug = analysis.verify_program(mlp_prog, feed=mlp_feed_arrays,
                                    fetch_list=mlp_fetch)
    codes = sorted({d.code for d in r_bug.errors})
    results["seeded_codes"] = codes
    if "AN101" not in codes:
        failures.append(f"seeded shape bug not caught (codes={codes})")

    # 4: CLI round-trip (in-process: same argument surface as
    # `python -m paddle_tpu.analysis lint`)
    from paddle_tpu.analysis.__main__ import main as cli_main

    rc = cli_main(["lint", "--model", "mlp", "--json"])
    if rc != 0:
        failures.append(f"CLI lint --model mlp exited {rc}")
    rc = cli_main(["--smoke"])
    if rc != 0:
        failures.append(f"CLI --smoke exited {rc}")

    # 5: latency gate
    durations.sort()
    p50 = durations[len(durations) // 2]
    results["verify_p50_ms"] = round(p50, 3)
    results["verify_max_ms"] = round(durations[-1], 3)
    if p50 >= 50.0:
        failures.append(f"verify p50 {p50:.1f}ms >= 50ms budget")

    results["wall_s"] = round(time.perf_counter() - t_start, 2)
    results["ok"] = not failures
    print(json.dumps(results))
    for f in failures:
        print("FAIL:", f, file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
