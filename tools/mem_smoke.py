"""Memory-observability smoke (CPU, 8 forced host devices, < 5 s).

The CI oracle for ISSUE 11's three tiers in one run:

 1. **compiled truth** — a GUARDED dp2×tp2 windowed training run must
    publish a nonzero ``memory.peak_bytes{mesh=dp2xtp2}`` gauge and a
    ``memory.profile`` run event read from the real
    ``compiled.memory_analysis()`` of the AOT window executable;
 2. **pre-flight** — the AN501 static estimate for the same program on
    the same mesh must land within a 4x factor band of the compiled
    per-device peak (the window stacks N_STEPS feeds the one-step
    estimate never sees, so the band is wider than the single-device
    cross-check test's 2x), and a seeded 1 MB budget must produce the
    exact AN502 over-budget code;
 3. **ledger** — the windowed run must leave ``memory.live_bytes`` /
    ``memory.live_high_water_bytes`` gauges and a ``memory.watermark``
    event whose ``counters`` field round-trips through the chrome-trace
    exporter as a ``"ph": "C"`` counter track.

Run directly (``python tools/mem_smoke.py``) or from tier-1 via
``tests/test_memory.py::test_mem_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_concurrency_optimized_scheduler" not in _flags:
    _flags += " --xla_cpu_enable_concurrency_optimized_scheduler=false"
os.environ["XLA_FLAGS"] = _flags
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 8
MESH = "dp2,tp2"


def main() -> dict:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu import observe

    t0 = time.perf_counter()
    root = tempfile.mkdtemp(prefix="mem_smoke_")
    prev_dir = os.environ.get("PADDLE_OBSERVE_DIR")
    os.environ["PADDLE_OBSERVE_DIR"] = root
    observe.reset()
    try:
        return _run(t0, root)
    finally:
        # in-process callers (tests) must not inherit the smoke's sink
        if prev_dir is None:
            os.environ.pop("PADDLE_OBSERVE_DIR", None)
        else:
            os.environ["PADDLE_OBSERVE_DIR"] = prev_dir
        observe.reset()


def _run(t0, root) -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import analysis, observe
    from paddle_tpu.fluid import guardian
    from paddle_tpu.fluid.parallel_executor import ParallelExecutor
    from paddle_tpu.observe.export import chrome_trace

    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 13
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
            loss, startup_program=startup)

    rng = np.random.RandomState(5)
    feed = {"x": rng.normal(size=(N_STEPS, 8, 16)).astype(np.float32),
            "y": rng.randint(0, 10, size=(N_STEPS, 8, 1)).astype(np.int64)}

    report = {"ok": False}
    scope = fluid.Scope()
    guardian.install(guardian.GuardianConfig(policy="skip"))
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            pe = ParallelExecutor(loss_name=loss.name, main_program=prog,
                                  mesh=MESH)
            pe.run_steps([loss], feed=feed, n_steps=N_STEPS,
                         feed_per_step=True)
            guardian.flush()
    finally:
        guardian.disable()

    label = pe.mesh_label
    gauges = observe.registry().snapshot()["gauges"]
    peak = gauges.get('memory.peak_bytes{mesh="%s"}' % label, 0)
    report["mesh"] = label
    report["peak_bytes"] = int(peak)
    report["peak_nonzero"] = peak > 0
    report["live_gauges"] = bool(
        gauges.get('memory.live_bytes{mesh="%s",scope="train"}' % label)
        and gauges.get('memory.live_high_water_bytes{mesh="%s",scope='
                       '"train"}' % label))

    # -- pre-flight estimate vs compiled truth (factor band) --
    est_report = analysis.verify_program(
        prog, feed={"x": feed["x"][0], "y": feed["y"][0]},
        fetch_list=[loss], mesh=MESH, kind="pe_run_steps")
    est = (est_report.memory_estimate or {}).get("peak_bytes", 0)
    report["estimate_bytes"] = int(est)
    ratio = est / peak if peak else 0.0
    report["estimate_ratio"] = round(ratio, 3)
    report["estimate_in_band"] = 0.25 <= ratio <= 4.0 if peak else False
    report["an501"] = "AN501" in {d.code for d in est_report.diagnostics}

    # -- seeded over-budget program -> exact AN502, error severity --
    os.environ["PADDLE_MEM_BUDGET_MB"] = "0.001"
    try:
        over = analysis.verify_program(
            prog, feed={"x": feed["x"][0], "y": feed["y"][0]},
            fetch_list=[loss], mesh=MESH, kind="pe_run_steps")
        report["an502"] = sorted({d.code for d in over.errors}) == ["AN502"]
    finally:
        del os.environ["PADDLE_MEM_BUDGET_MB"]

    # -- chrome trace round-trips the memory counter track --
    sink = observe.get_sink()
    sink.flush()
    recs = [json.loads(line) for line in open(sink.events.path)]
    report["watermark_events"] = sum(
        1 for r in recs if r.get("event") == "memory.watermark")
    report["profile_events"] = sum(
        1 for r in recs if r.get("event") == "memory.profile")
    trace = json.loads(json.dumps(chrome_trace(recs)))
    tracks = {e["name"] for e in trace["traceEvents"]
              if e.get("ph") == "C"}
    report["counter_track"] = any(
        name.startswith("memory.live_bytes") for name in tracks)

    report["elapsed_s"] = round(time.perf_counter() - t0, 2)
    report["ok"] = bool(
        report["peak_nonzero"] and report["live_gauges"]
        and report["estimate_in_band"] and report["an501"]
        and report["an502"] and report["watermark_events"] >= 1
        and report["profile_events"] >= 1 and report["counter_track"])
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
