"""Probe: does Mosaic/Pallas compile over the axon tunnel?

Tiny flash_attention forward + backward vs the jnp reference, then a
timed bench-shaped call (transformer-base head geometry) against the XLA
attention it would replace.  Emits one JSON line per stage; first failure
emits {"stage": ..., "ok": false, "error": ...} and exits nonzero so the
bench gate (BENCH_FLASH) stays off.

Usage: python tools/flash_probe.py   (PROBE_PLATFORM=cpu for smoke)
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

if os.environ.get("PROBE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.ops.pallas_flash import flash_attention  # noqa: E402


def emit(**kw):
    print(json.dumps(kw), flush=True)


def ref_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", jnp.float32(q),
                        jnp.float32(k)) * scale
    if causal:
        tq, tk = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, jnp.float32(v)).astype(q.dtype)


def stage(name, fn):
    t0 = time.time()
    try:
        out = fn()
    except Exception as e:  # noqa: BLE001 — probe must report, not crash
        emit(stage=name, ok=False, secs=round(time.time() - t0, 2),
             error=f"{type(e).__name__}: {e}"[:400])
        sys.exit(1)
    emit(stage=name, ok=True, secs=round(time.time() - t0, 2), **(out or {}))


def main():
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)

    # --- tiny correctness: fwd ---
    b, h, t, d = 2, 4, 256, 64
    q = jax.random.normal(kq, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, t, d), jnp.bfloat16)

    def tiny_fwd():
        out = jax.jit(flash_attention)(q, k, v).block_until_ready()
        ref = ref_attention(q, k, v)
        err = float(jnp.max(jnp.abs(jnp.float32(out) - jnp.float32(ref))))
        assert err < 0.05, f"fwd max err {err}"
        return {"max_err": round(err, 5)}

    stage("tiny_fwd", tiny_fwd)

    def tiny_causal():
        fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        out = fa(q, k, v).block_until_ready()
        ref = ref_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(jnp.float32(out) - jnp.float32(ref))))
        assert err < 0.05, f"causal max err {err}"
        return {"max_err": round(err, 5)}

    stage("tiny_causal", tiny_causal)

    # --- tiny backward ---
    def tiny_bwd():
        def loss_flash(q, k, v):
            return jnp.float32(flash_attention(q, k, v)).sum()

        def loss_ref(q, k, v):
            return jnp.float32(ref_attention(q, k, v)).sum()

        gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        errs = [float(jnp.max(jnp.abs(jnp.float32(a) - jnp.float32(b))))
                for a, b in zip(gf, gr)]
        assert max(errs) < 0.1, f"bwd max errs {errs}"
        return {"max_err": round(max(errs), 5)}

    stage("tiny_bwd", tiny_bwd)

    # --- bench-shaped timing: transformer-base geometry ---
    b, h, t, d = 64, 8, 256, 64
    q = jax.random.normal(kq, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, t, d), jnp.bfloat16)
    # attention FLOPs: 2 matmuls of 2*b*h*t*t*d each; train ~3x fwd
    flops = 2 * 2 * b * h * t * t * d

    def timed(fn, n=20):
        fn()  # compile + warm
        t0 = time.time()
        for _ in range(n):
            r = fn()
        jax.tree.map(lambda a: a.block_until_ready(), r)
        return (time.time() - t0) / n

    def bench_pair():
        def train_flash(q, k, v):
            return jax.grad(
                lambda q: jnp.float32(flash_attention(q, k, v)).sum())(q)

        def train_ref(q, k, v):
            return jax.grad(
                lambda q: jnp.float32(ref_attention(q, k, v)).sum())(q)

        jf = jax.jit(train_flash)
        jr = jax.jit(train_ref)
        sf = timed(lambda: jf(q, k, v))
        sr = timed(lambda: jr(q, k, v))
        return {
            "flash_ms": round(sf * 1e3, 3),
            "xla_ms": round(sr * 1e3, 3),
            "flash_tflops": round(3 * flops / sf / 1e12, 2),
            "xla_tflops": round(3 * flops / sr / 1e12, 2),
            "speedup": round(sr / sf, 3),
        }

    stage("bench_train_shape", bench_pair)


if __name__ == "__main__":
    main()
