"""Checkpointable data-plane smoke (CPU, < 5 s).

The CI oracle for the streaming input pipeline (ISSUE 10): a sharded +
shuffled + batched + device-prefetched pipeline must (a) partition the
dataset across shards with no overlap and no loss, (b) round-trip its
cursor through ``state()``/``restore()`` mid-epoch — the restored
pipeline yields the byte-identical tail of an uninterrupted run, even
though the prefetcher had staged windows past the commit point — and
(c) reproduce epoch N's shuffled order directly, with no replay of
earlier epochs.

Run directly (``python tools/data_smoke.py``) or from tier-1 via
``tests/test_data_pipeline.py::test_data_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SAMPLES = 128
BATCH = 4
N_STEPS = 2  # window size for the prefetcher


def main() -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import data

    t0 = time.perf_counter()

    def sample_reader():
        for i in range(N_SAMPLES):
            yield (np.full((3,), i, np.float32), i)

    def build(shard_index=0):
        return (data.from_reader(sample_reader)
                    .shard(2, shard_index)
                    .shuffle(16, seed=11)
                    .batch(BATCH))

    def ids(batches):
        return [s[1] for b in batches for s in b]

    # (a) shards partition the dataset: no overlap, no loss
    shard_ids = [set(ids(list(iter(build(i))))) for i in range(2)]
    partition_ok = (not (shard_ids[0] & shard_ids[1])
                    and shard_ids[0] | shard_ids[1] == set(range(N_SAMPLES)))

    # (b) prefetched checkpoint/restore round trip: consume 3 windows,
    # commit pf.last_state, restore a FRESH pipeline there — consumed +
    # restored-tail must equal the uninterrupted sequence exactly
    ref = ids(list(iter(build())))
    pipe = build()
    feeds = ({"x": np.stack([s[0] for s in b]),
              "i": np.array([s[1] for s in b])} for b in pipe())
    consumed = []
    with data.CheckpointablePrefetcher(feeds, pipe, n_steps=N_STEPS,
                                       place=fluid.CPUPlace(),
                                       depth=2) as pf:
        for k, (feed_dev, count) in enumerate(pf):
            consumed.extend(int(x) for x in
                            np.asarray(feed_dev["i"]).reshape(-1))
            if k == 2:
                state = pf.last_state
                break
    restored = build()
    restored.restore(state)
    tail = ids(list(restored()))
    resume_ok = consumed + tail == ref

    # (c) epoch 1's order reproduces directly (no epoch-0 replay) and
    # differs from epoch 0's
    two_epochs = build()
    e0 = ids(list(two_epochs()))
    e1 = ids(list(two_epochs()))
    direct = build()
    direct.set_epoch(1)
    epoch_ok = ids(list(iter(direct))) == e1 and e0 != e1

    report = {
        "ok": bool(partition_ok and resume_ok and epoch_ok),
        "partition_ok": bool(partition_ok),
        "resume_ok": bool(resume_ok),
        "epoch_ok": bool(epoch_ok),
        "consumed_before_restore": len(consumed),
        "shard_sizes": [len(s) for s in shard_ids],
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
