#!/usr/bin/env python
"""Paged KV-cache smoke (CPU, < 10 s) — the ISSUE 19 CI oracle.

A churn workload through a PAGED DecodeEngine (more requests than
slots, mixed prompt lengths, so admissions land in a fragmented free
list) checked three ways:

 - every generated stream is BITWISE identical to per-request
   sequential decode on a DENSE engine over the same config/seed (the
   page indirection moves where K/V rows live, never what they contain);
 - a shared-prompt batch drives the prefix-sharing index:
   ``prefix_hits`` goes nonzero and full-prefix admissions skip their
   prefill dispatch outright (``prefill_skips``);
 - after the engine drains, ``kvpool.pages_free`` returns EXACTLY to
   the initial pool size — no page is leaked by admit/retire churn.

Run directly (``python tools/paged_smoke.py``) or from tier-1 via
``tests/test_kvpool.py::test_paged_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SLOTS = 3
MAX_LEN = 32
BUCKETS = [4, 8]
PAGE_SIZE = 4


def _jobs(vocab):
    import numpy as np

    rng = np.random.RandomState(19)
    lengths = [3, 5, 8, 4, 6, 3]
    news = [5, 4, 6, 4, 5, 6]
    return [([int(t) for t in rng.randint(2, vocab - 1, size=n)], m)
            for n, m in zip(lengths, news)]


def main() -> dict:
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import DecodeEngine

    t_start = time.perf_counter()
    report = {"ok": False}
    dense = paged = None
    try:
        def build(is_paged):
            model = transformer.DecodeModel(
                cfg=transformer.decode_lm_config(), max_slots=SLOTS,
                max_len=MAX_LEN, prefill_buckets=list(BUCKETS),
                paged=is_paged, page_size=PAGE_SIZE)
            return DecodeEngine(model)

        dense = build(False)
        paged = build(True)
        pool = paged._pool
        report["num_pages"] = pool.num_pages
        report["pages_free_initial"] = pool.pages_free

        jobs = _jobs(dense.model.vocab_size)
        # dense per-request sequential decode: the bitwise oracle
        sequential = [dense.decode_static([j])[0][0] for j in jobs]

        # churn: twice the slot count in flight forces waves of
        # admit/retire and fragmented re-allocation of freed pages
        futs = [paged.submit(p, n) for p, n in jobs]
        outs = [f.result(timeout=60) for f in futs]
        report["bitwise_vs_dense"] = outs == sequential

        # shared-prompt batch: prompt length 5 with page_size 4 leaves
        # one shareable full page AND (plen-1) % page_size == 0, so
        # later admissions are full hits that skip prefill entirely
        shared = jobs[1][0]
        futs = [paged.submit(shared, 4) for _ in range(SLOTS)]
        shared_outs = [f.result(timeout=60) for f in futs]
        report["shared_outputs_identical"] = all(
            o == shared_outs[0] for o in shared_outs)
        snap = paged.metrics.snapshot()
        report["prefix_hits"] = snap["prefix_hits"]
        report["prefill_skips"] = snap["prefill_skips"]

        paged.wait_idle(timeout_s=30)
        report["pages_free_after_drain"] = pool.pages_free
        report["pages_leaked"] = pool.pages_leaked
        report["kvpool_hbm_bytes"] = snap.get("kvpool_hbm_bytes")
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["bitwise_vs_dense"]
            and report["shared_outputs_identical"]
            and report["prefix_hits"] > 0
            and report["prefill_skips"] > 0
            and report["pages_free_after_drain"]
            == report["pages_free_initial"]
            and report["pages_leaked"] == 0)
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        for eng in (dense, paged):
            if eng is not None:
                try:
                    eng.shutdown(timeout_s=10)
                except Exception:
                    pass
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
