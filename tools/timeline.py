"""Convert paddle_tpu profile event logs to ONE chrome://tracing JSON file.

ref: tools/timeline.py (_ChromeTraceFormatter :36, Timeline :115) — the
reference converts its profiler proto into the Chrome trace-event format;
this converts the JSON event logs written by
``fluid.profiler.stop_profiler(profile_path=...)``.  The device-side trace
(XLA ops) lives in the jax trace_dir referenced by each log and opens in
TensorBoard/perfetto directly.

Multi-host (ISSUE 5): pass several logs and each gets its own pid with a
``process_name`` metadata row (named from the ``host`` field the profiler
stamps, falling back to the file name), so a pod's host timelines line up
in one view instead of all collapsing onto pid 0.  Counter samples recorded
during the profiling session (queue depth, cache hits ... over time) become
``"ph": "C"`` counter tracks on their host's pid.

Usage: python tools/timeline.py --profile_path /tmp/p0 [/tmp/p1 ...] \
                                --timeline_path /tmp/timeline.json
"""

from __future__ import annotations

import argparse
import json
import os


class ChromeTraceFormatter:
    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append({"name": "process_name", "ph": "M",
                               "pid": pid, "args": {"name": name}})

    def emit_region(self, timestamp, duration, pid, tid, category, name,
                    args=None):
        self._events.append({"ph": "X", "cat": category, "ts": timestamp,
                             "dur": duration, "pid": pid, "tid": tid,
                             "name": name, "args": args or {}})

    def emit_counter(self, timestamp, pid, name, value):
        self._events.append({"ph": "C", "ts": timestamp, "pid": pid,
                             "name": name, "args": {"value": value}})

    def format_to_string(self, pretty=False):
        trace = {"traceEvents": self._metadata + self._events}
        return json.dumps(trace, indent=4 if pretty else None,
                          separators=None if pretty else (",", ":"))


class Timeline:
    """``logs`` is a list of (label, log-dict) pairs — one per host profile
    file; each pair becomes one pid in the merged trace."""

    def __init__(self, logs):
        if isinstance(logs, dict):  # single pre-parsed log (legacy callers)
            logs = [("paddle_tpu:host", logs)]
        self._logs = list(logs)
        self._chrome = ChromeTraceFormatter()

    def generate_chrome_trace(self) -> str:
        for pid, (label, log) in enumerate(self._logs):
            host = log.get("host") or label
            self._chrome.emit_pid(f"paddle_tpu:{host}", pid)
            for ev in log.get("events", []):
                # spans render as complete ("X") events on their OWN
                # thread row (the profiler stamps tid per emitting
                # thread), so prefetch-worker staging no longer overlaps
                # executor dispatch on one track; legacy logs without a
                # tid keep row 0
                self._chrome.emit_region(ev["ts"], ev["dur"], pid,
                                         ev.get("tid", 0), "Op",
                                         ev["name"])
            for s in log.get("counters", []):
                self._chrome.emit_counter(s["ts"], pid, s["name"],
                                          s["value"])
        return self._chrome.format_to_string()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True, nargs="+",
                   help="JSON log(s) written by fluid.profiler."
                        "stop_profiler — one per host for a merged view")
    p.add_argument("--timeline_path", required=True,
                   help="chrome://tracing output file")
    args = p.parse_args()
    logs = []
    for path in args.profile_path:
        with open(path) as f:
            logs.append((os.path.basename(path), json.load(f)))
    tl = Timeline(logs)
    with open(args.timeline_path, "w") as f:
        f.write(tl.generate_chrome_trace())
    for _, log in logs:
        if log.get("trace_dir"):
            print(f"device trace (open in TensorBoard/perfetto): "
                  f"{log['trace_dir']}")


if __name__ == "__main__":
    main()
