"""Convert a paddle_tpu profile event log to a chrome://tracing JSON file.

ref: tools/timeline.py (_ChromeTraceFormatter :36, Timeline :115) — the
reference converts its profiler proto into the Chrome trace-event format;
this converts the JSON event log written by
``fluid.profiler.stop_profiler(profile_path=...)``.  The device-side trace
(XLA ops) lives in the jax trace_dir referenced by the log and opens in
TensorBoard/perfetto directly.

Usage: python tools/timeline.py --profile_path /tmp/profile \
                                --timeline_path /tmp/timeline.json
"""

from __future__ import annotations

import argparse
import json


class ChromeTraceFormatter:
    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append({"name": "process_name", "ph": "M",
                               "pid": pid, "args": {"name": name}})

    def emit_region(self, timestamp, duration, pid, tid, category, name,
                    args=None):
        self._events.append({"ph": "X", "cat": category, "ts": timestamp,
                             "dur": duration, "pid": pid, "tid": tid,
                             "name": name, "args": args or {}})

    def format_to_string(self, pretty=False):
        trace = {"traceEvents": self._metadata + self._events}
        return json.dumps(trace, indent=4 if pretty else None,
                          separators=None if pretty else (",", ":"))


class Timeline:
    def __init__(self, events):
        self._events = events
        self._chrome = ChromeTraceFormatter()

    def generate_chrome_trace(self) -> str:
        self._chrome.emit_pid("paddle_tpu:host", 0)
        for ev in self._events:
            self._chrome.emit_region(ev["ts"], ev["dur"], 0, 0, "Op",
                                     ev["name"])
        return self._chrome.format_to_string()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--profile_path", required=True,
                   help="JSON written by fluid.profiler.stop_profiler")
    p.add_argument("--timeline_path", required=True,
                   help="chrome://tracing output file")
    args = p.parse_args()
    with open(args.profile_path) as f:
        log = json.load(f)
    tl = Timeline(log.get("events", []))
    with open(args.timeline_path, "w") as f:
        f.write(tl.generate_chrome_trace())
    if log.get("trace_dir"):
        print(f"device trace (open in TensorBoard/perfetto): "
              f"{log['trace_dir']}")


if __name__ == "__main__":
    main()
