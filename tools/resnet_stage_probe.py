"""Per-stage ResNet-50 MFU probe (round-5 isolation #3).

The train-step-structure ablation cleared BN/backward/momentum (66-89%
MFU on synthetic uniform chains) and the conv-fusion probe cleared
elementwise fusion (147 TFLOPs), yet the full ResNet-50 step sits at
~21-27 TFLOPs.  The remaining suspects are the REAL geometry's stages.
This probe jits each piece of the network in isolation — stem (7x7/2 +
maxpool), stage1..4 bottleneck groups, head (pool+fc) — as its own
fwd+bwd step at bs256, and reports per-stage TFLOPs against each
stage's analytic FLOPs, so the MFU sink is localized to a stage (or
shown to be none of them, pointing at whole-program scheduling).

Usage: python tools/resnet_stage_probe.py [BATCH STEPS]
PROBE_PLATFORM=cpu for smoke runs (tiny shapes).
PROBE_SINK=path.jsonl appends result lines (survives kills).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax

if os.environ.get("PROBE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])

import jax.numpy as jnp
import numpy as np
from jax import lax

SMOKE = os.environ.get("PROBE_PLATFORM") == "cpu"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else (4 if SMOKE else 256)
STEPS = int(sys.argv[2]) if len(sys.argv) > 2 else (2 if SMOKE else 12)
DN = ("NCHW", "OIHW", "NCHW")
BLOCKS = [3, 4, 6, 3]
WIDTHS = [64, 128, 256, 512]


def emit(**kw):
    line = json.dumps(kw)
    print(line, flush=True)
    sink = os.environ.get("PROBE_SINK")
    if sink:
        try:
            with open(sink, "a") as f:
                f.write(line + "\n")
        except OSError as e:
            print(f"# PROBE_SINK write failed: {e}", flush=True)


def note(msg):
    print(f"# {msg} [{time.strftime('%H:%M:%S')}]", flush=True)


def conv(x, w, stride):
    return lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride), "SAME",
        dimension_numbers=DN)


def bn_relu(x, g, b, relu=True):
    xf = jnp.float32(x)
    mean = xf.mean(axis=(0, 2, 3), keepdims=True)
    var = xf.var(axis=(0, 2, 3), keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + 1e-5)
    y = (y * g[None, :, None, None] + b[None, :, None, None]).astype(
        jnp.bfloat16)
    return jax.nn.relu(y) if relu else y


def make_cb(key, cin, cout, k):
    kw, key = jax.random.split(key)
    return {"w": jax.random.normal(kw, (cout, cin, k, k), jnp.float32)
            * np.sqrt(2.0 / (cin * k * k)),
            "g": jnp.ones((cout,), jnp.float32),
            "b": jnp.zeros((cout,), jnp.float32)}, key


def conv_flops(n, cin, cout, k, h_out, w_out):
    return 2.0 * n * cin * cout * k * k * h_out * w_out


def stage_fn(params, x, si):
    for bi in range(BLOCKS[si]):
        blk = params[f"b{bi}"]
        stride = 2 if (bi == 0 and si > 0) else 1
        short = x
        if "sc" in blk:
            short = bn_relu(conv(x, blk["sc"]["w"], stride),
                            blk["sc"]["g"], blk["sc"]["b"], relu=False)
        y = bn_relu(conv(x, blk["c1"]["w"], stride), blk["c1"]["g"],
                    blk["c1"]["b"])
        y = bn_relu(conv(y, blk["c2"]["w"], 1), blk["c2"]["g"],
                    blk["c2"]["b"])
        y = bn_relu(conv(y, blk["c3"]["w"], 1), blk["c3"]["g"],
                    blk["c3"]["b"], relu=False)
        x = jax.nn.relu(short + y)
    return x


def make_stage_params(key, si, cin):
    width = WIDTHS[si]
    params = {}
    for bi in range(BLOCKS[si]):
        blk = {}
        blk["c1"], key = make_cb(key, cin, width, 1)
        blk["c2"], key = make_cb(key, width, width, 3)
        blk["c3"], key = make_cb(key, width, width * 4, 1)
        if bi == 0:
            blk["sc"], key = make_cb(key, cin, width * 4, 1)
        params[f"b{bi}"] = blk
        cin = width * 4
    return params, key, cin


def stage_flops(si, n, hw_in, cin):
    """Analytic fwd conv FLOPs of stage si with input [n,cin,hw,hw]."""
    total = 0.0
    width = WIDTHS[si]
    hw = hw_in
    for bi in range(BLOCKS[si]):
        stride = 2 if (bi == 0 and si > 0) else 1
        hw_out = hw // stride
        if bi == 0:
            total += conv_flops(n, cin, width * 4, 1, hw_out, hw_out)
        total += conv_flops(n, cin, width, 1, hw_out, hw_out)
        total += conv_flops(n, width, width, 3, hw_out, hw_out)
        total += conv_flops(n, width, width * 4, 1, hw_out, hw_out)
        cin = width * 4
        hw = hw_out
    return total, hw, cin


def timed_step(fn, params, x, flops_fwd, label):
    def loss_fn(p, inp):
        return jnp.float32(fn(p, inp)).mean()

    step = jax.jit(jax.value_and_grad(loss_fn))
    t0 = time.time()
    out = step(params, x)
    jax.block_until_ready(out)
    compile_s = time.time() - t0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = step(params, x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    # train ≈ 3x fwd conv FLOPs (bench accounting)
    tflops = 3.0 * flops_fwd * STEPS / dt / 1e12
    emit(variant=label, ms_per_step=round(dt / STEPS * 1e3, 2),
         tflops=round(tflops, 1), compile_s=round(compile_s, 1),
         device=jax.devices()[0].platform)


def main():
    rng = np.random.RandomState(0)
    hw = 32 if SMOKE else 224
    key = jax.random.PRNGKey(0)

    # stem: 7x7/2 conv + 3x3/2 maxpool
    note("stem")
    stem, key = make_cb(key, 3, 64, 7)

    def stem_fn(p, x):
        y = bn_relu(conv(x, p["w"], 2), p["g"], p["b"])
        return lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 3, 3),
                                 (1, 1, 2, 2), "SAME")

    x = jnp.asarray(rng.normal(size=(BATCH, 3, hw, hw)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    timed_step(stem_fn, stem, x,
               conv_flops(BATCH, 3, 64, 7, hw // 2, hw // 2), "stem")

    hw_s, cin = hw // 4, 64
    for si in range(4):
        note(f"stage{si + 1}")
        params, key, cout = make_stage_params(key, si, cin)
        x = jnp.asarray(rng.normal(
            size=(BATCH, cin, hw_s, hw_s)).astype(np.float32)
        ).astype(jnp.bfloat16)
        flops, hw_out, _ = stage_flops(si, BATCH, hw_s, cin)
        timed_step(functools.partial(stage_fn, si=si), params, x, flops,
                   f"stage{si + 1}_{hw_s}px_c{cin}")
        hw_s, cin = hw_out, cout

    # head: global pool + fc
    note("head")
    kfc, key = jax.random.split(key)
    head = {"w": jax.random.normal(kfc, (2048, 1000), jnp.float32) * 0.01}

    def head_fn(p, x):
        pooled = jnp.float32(x).mean(axis=(2, 3))
        return pooled @ p["w"]

    x = jnp.asarray(rng.normal(
        size=(BATCH, 2048 if not SMOKE else cin, hw_s, hw_s))
        .astype(np.float32)).astype(jnp.bfloat16)
    if SMOKE:
        head["w"] = jnp.zeros((cin, 10), jnp.float32)
    timed_step(head_fn, head, x,
               2.0 * BATCH * (2048 if not SMOKE else cin)
               * (1000 if not SMOKE else 10), "head")


if __name__ == "__main__":
    main()
