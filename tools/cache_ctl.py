"""Operator CLI for the persistent compile cache (paddle_tpu.compile_cache).

Commands (default root: $PADDLE_COMPILE_CACHE_DIR, overridable via --dir)::

    python tools/cache_ctl.py ls                  # one line per entry
    python tools/cache_ctl.py stats               # sizes / counts JSON
    python tools/cache_ctl.py verify              # checksum every entry
    python tools/cache_ctl.py prune [--budget-mb N]
                                                  # drop incomplete/corrupt
                                                  # entries + LRU-evict
    python tools/cache_ctl.py clear               # wipe the whole root
    python tools/cache_ctl.py --smoke             # CI round-trip oracle

``--smoke`` is the tier-1 oracle (mirrors ``tools/replay_smoke.py``): in a
temp root it populates the cache by running a tiny MLP train step twice
(cold then warm), then drives stats -> verify -> a deliberate corruption ->
verify -> prune -> clear through the same code paths an operator would,
printing one JSON report and exiting non-zero on any failed check.  Must
finish in well under 10 s on CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _store(args):
    from paddle_tpu.compile_cache import CompileCacheStore

    root = args.dir or os.environ.get("PADDLE_COMPILE_CACHE_DIR", "").strip()
    if not root:
        print(json.dumps({"error": "no cache dir: pass --dir or set "
                                   "PADDLE_COMPILE_CACHE_DIR"}))
        raise SystemExit(2)
    return CompileCacheStore(root, args.budget_mb)


def cmd_ls(args) -> int:
    store = _store(args)
    rows = []
    for rec in store.entries():
        m = rec["manifest"] or {}
        rows.append({"fingerprint": rec["fingerprint"],
                     "complete": rec["complete"],
                     "bytes": rec["bytes"],
                     "kind": m.get("kind"),
                     "compile_seconds": m.get("compile_seconds"),
                     "created": m.get("created")})
    print(json.dumps(rows, indent=1))
    return 0


def cmd_stats(args) -> int:
    print(json.dumps(_store(args).stats(), indent=1))
    return 0


def cmd_verify(args) -> int:
    store = _store(args)
    report = {rec["fingerprint"]: store.verify_entry(rec["fingerprint"])
              for rec in store.entries()}
    bad = {fp: st for fp, st in report.items() if st != "ok"}
    print(json.dumps({"entries": len(report), "bad": bad}, indent=1))
    return 0 if not bad else 1


def cmd_prune(args) -> int:
    store = _store(args)
    budget = (None if args.budget_mb is None
              else int(float(args.budget_mb) * (1 << 20)))
    print(json.dumps(store.prune(budget), indent=1))
    return 0


def cmd_clear(args) -> int:
    store = _store(args)
    store.clear()
    print(json.dumps({"cleared": store.root}))
    return 0


def _smoke_populate(root):
    """Run a tiny MLP train step against ``root`` twice (fresh Executor the
    second time) and return the cache counter deltas."""
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.fluid as fluid
    from paddle_tpu import compile_cache
    from paddle_tpu.fluid import profiler

    compile_cache.configure(root)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=8, act="relu")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.normal(size=(4, 8)).astype(np.float32),
            "y": rng.normal(size=(4, 1)).astype(np.float32)}

    def one_pass():
        before = profiler.counters()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(prog, feed=feed, fetch_list=[loss])
        after = profiler.counters()
        return {k: after.get(f"compile_cache.{k}", 0)
                - before.get(f"compile_cache.{k}", 0)
                for k in ("hit", "miss", "put", "corrupt_fallback")}

    return one_pass(), one_pass()


def cmd_smoke(_args) -> int:
    import shutil
    import tempfile

    t_start = time.perf_counter()
    root = tempfile.mkdtemp(prefix="cache_ctl_smoke_")
    ns = argparse.Namespace(dir=root, budget_mb=None)
    report = {"ok": False, "root": root}
    try:
        cold, warm = _smoke_populate(root)
        report["cold"], report["warm"] = cold, warm
        store = _store(ns)
        report["stats"] = store.stats()
        verify0 = {r["fingerprint"]: store.verify_entry(r["fingerprint"])
                   for r in store.entries()}
        report["verify_clean"] = all(v == "ok" for v in verify0.values())
        # corrupt one payload on disk; verify must flag it, prune must
        # remove it, and the stale fingerprint must re-load as a miss
        victim = store.entries()[0]["fingerprint"]
        with open(os.path.join(store.entry_dir(victim), "program.bin"),
                  "wb") as f:
            f.write(b"garbage")
        report["verify_flags_corruption"] = \
            store.verify_entry(victim).startswith("corrupt")
        pruned = store.prune()
        report["prune_removed"] = [r["fingerprint"]
                                   for r in pruned["removed"]]
        store.clear()
        report["cleared_empty"] = (store.stats()["entries"] == 0)
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = (
            cold["miss"] >= 2 and cold["hit"] == 0
            and warm["hit"] == cold["miss"] and warm["miss"] == 0
            and report["verify_clean"]
            and report["verify_flags_corruption"]
            and victim in report["prune_removed"]
            and report["cleared_empty"])
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Inspect / maintain the persistent compile cache.")
    ap.add_argument("command", nargs="?", default="stats",
                    choices=["ls", "stats", "verify", "prune", "clear"])
    ap.add_argument("--dir", default=None,
                    help="cache root (default $PADDLE_COMPILE_CACHE_DIR)")
    ap.add_argument("--budget-mb", type=float, default=None,
                    help="size budget for prune / stats")
    ap.add_argument("--smoke", action="store_true",
                    help="CI round-trip: populate -> stats -> verify -> "
                         "prune -> clear in a temp root")
    args = ap.parse_args(argv)
    if args.smoke:
        return cmd_smoke(args)
    return {"ls": cmd_ls, "stats": cmd_stats, "verify": cmd_verify,
            "prune": cmd_prune, "clear": cmd_clear}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
