"""Pod/cluster launch plan generator for the PADDLE_* multihost contract.

TPU-native replacement for the reference's cluster launchers
(ref: benchmark/fluid/kube_gen_job.py:1 — pserver/trainer k8s yaml pairs;
tools/aws_benchmarking/ — EC2 cluster bring-up).  There are no pservers
here: every process is a symmetric trainer that joins ONE
jax.distributed coordination service (paddle_tpu.parallel.multihost), so
the launcher's whole job is to hand each host the same command with the
right four env vars:

    PADDLE_TRAINER_ID        this process's rank            (0..N-1)
    PADDLE_TRAINERS          world size N
    PADDLE_COORDINATOR_ADDR  host0:port — the coordination service
    PADDLE_LOCAL_DEVICE_IDS  optional comma list pinning local chips

Library surface (used by tests/test_dist_4proc.py-style subprocess
oracles so the launch plan itself is exercised):

    make_launch_plan(hosts, entry, port=12355, devices_per_host=None)
        -> [{"host", "trainer_id", "env": {...}, "cmd": [...]}, ...]

CLI:

    python tools/pod_launch.py --hosts tpu-a,tpu-b --entry "python train.py"
    python tools/pod_launch.py --hosts ... --format k8s   # Job manifests
    python tools/pod_launch.py --hosts ... --format ssh   # ssh one-liners

`--format env` (default) prints per-host `env VAR=... cmd` lines;
`k8s` emits one YAML Job per host as an indexed StatefulSet-style list
(mirroring kube_gen_job.py's per-role manifests, minus the pserver half);
`ssh` prints ready-to-paste ssh lines; `elastic` emits a single
`paddle_tpu.parallel.elastic` supervisor command that owns the whole
(local) pod — launch, heartbeat watch, checkpoint auto-resume.
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
from typing import Dict, List, Optional, Sequence


def make_launch_plan(hosts: Sequence[str], entry: str,
                     port: int = 12355,
                     devices_per_host: Optional[int] = None,
                     extra_env: Optional[Dict[str, str]] = None) -> List[dict]:
    """One plan entry per host: rank i, coordinator = hosts[0]:port.

    The coordinator address uses the FIRST host for every rank (including
    rank 0 itself) — the same convention as the reference's PSERVER_EPS
    first-endpoint fallback (paddle_tpu.parallel.multihost.init).
    """
    hosts = [h.strip() for h in hosts if h.strip()]
    if not hosts:
        raise ValueError("pod_launch: empty host list")
    coordinator = f"{hosts[0]}:{port}"
    plan = []
    for i, host in enumerate(hosts):
        env = {
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS": str(len(hosts)),
            "PADDLE_COORDINATOR_ADDR": coordinator,
        }
        if devices_per_host:
            env["PADDLE_LOCAL_DEVICE_IDS"] = ",".join(
                str(d) for d in range(devices_per_host))
        if extra_env:
            env.update(extra_env)
        plan.append({"host": host, "trainer_id": i, "env": env,
                     "cmd": shlex.split(entry)})
    return plan


def format_env(plan: List[dict]) -> str:
    lines = []
    for p in plan:
        envs = " ".join(f"{k}={v}" for k, v in sorted(p["env"].items()))
        cmd = " ".join(shlex.quote(c) for c in p["cmd"])
        lines.append(f"# host {p['host']} (rank {p['trainer_id']})")
        lines.append(f"env {envs} {cmd}")
    return "\n".join(lines)


def format_ssh(plan: List[dict]) -> str:
    lines = []
    for p in plan:
        envs = " ".join(f"{k}={v}" for k, v in sorted(p["env"].items()))
        cmd = " ".join(shlex.quote(c) for c in p["cmd"])
        lines.append(f"ssh {p['host']} {shlex.quote(f'env {envs} {cmd}')}")
    return "\n".join(lines)


def format_elastic(plan: List[dict], workdir: str = "./elastic_run") -> str:
    """One supervisor line replacing N per-host lines: hand the pod to
    ``paddle_tpu.parallel.elastic``, which relaunches it with this same
    env contract, watches heartbeats, and auto-resumes from the newest
    complete sharded checkpoint (docs/ROBUSTNESS.md).  Local
    (single-machine) pods only — the k8s/ssh formats stay the multi-host
    path, with the supervisor run per site."""
    entry = " ".join(shlex.quote(c) for c in plan[0]["cmd"])
    passthrough = [f"--env {shlex.quote(k + '=' + v)}"
                   for k, v in sorted(plan[0]["env"].items())
                   if k not in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS",
                                "PADDLE_COORDINATOR_ADDR")]
    parts = [f"python -m paddle_tpu.parallel.elastic --nproc {len(plan)}",
             f"--entry {shlex.quote(entry)}",
             f"--workdir {shlex.quote(workdir)}"] + passthrough
    return " \\\n    ".join(parts)


def format_k8s(plan: List[dict], jobname: str = "paddlejob",
               image: str = "paddle-tpu:latest",
               cpu: int = 4, memory_gi: int = 8) -> str:
    """One k8s Job per rank (the trainer half of kube_gen_job.py's output;
    there is no pserver role).  Hostnames in the plan become the
    coordinator service DNS name for rank routing; the rank-0 Job also
    carries the coordinator port so a headless Service can target it."""
    docs = []
    port = plan[0]["env"]["PADDLE_COORDINATOR_ADDR"].rsplit(":", 1)[1]
    for p in plan:
        env_list = [{"name": k, "value": v}
                    for k, v in sorted(p["env"].items())]
        container = {
            "name": f"{jobname}-trainer",
            "image": image,
            "command": p["cmd"],
            "env": env_list,
            "resources": {"requests": {"cpu": str(cpu),
                                       "memory": f"{memory_gi}Gi"}},
        }
        if p["trainer_id"] == 0:
            container["ports"] = [{"containerPort": int(port),
                                   "name": "coordinator"}]
        docs.append({
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {"name": f"{jobname}-{p['trainer_id']}",
                         "labels": {"paddle-job": jobname,
                                    "rank": str(p["trainer_id"])}},
            "spec": {"template": {
                "metadata": {"labels": {"paddle-job": jobname}},
                # hostNetwork: the coordinator address is hosts[0]:port (a
                # NODE name); without host networking the rank-0 listener
                # binds a pod IP that the address never resolves to
                "spec": {"restartPolicy": "Never",
                         "hostNetwork": True,
                         "nodeSelector": {"kubernetes.io/hostname":
                                          p["host"]},
                         "containers": [container]}}},
        })
    # plain-JSON YAML subset: json is valid YAML, one doc per Job
    return "\n---\n".join(json.dumps(d, indent=2) for d in docs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Generate per-host launch commands for the PADDLE_* "
                    "multihost contract (no pservers: symmetric trainers "
                    "joining one jax.distributed coordinator).")
    ap.add_argument("--hosts", required=True,
                    help="comma-separated host list; hosts[0] is the "
                         "coordinator")
    ap.add_argument("--entry", default="python train.py",
                    help="training command each host runs")
    ap.add_argument("--port", type=int, default=12355,
                    help="coordination-service port on hosts[0]")
    ap.add_argument("--devices-per-host", type=int, default=None,
                    help="pin PADDLE_LOCAL_DEVICE_IDS=0..D-1 on every host")
    ap.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="extra env var(s) for every host")
    ap.add_argument("--format", choices=("env", "ssh", "k8s", "elastic"),
                    default="env")
    ap.add_argument("--jobname", default="paddlejob")
    ap.add_argument("--image", default="paddle-tpu:latest")
    ap.add_argument("--workdir", default="./elastic_run",
                    help="supervisor workdir for --format elastic")
    args = ap.parse_args(argv)

    extra = {}
    for kv in args.env:
        if "=" not in kv:
            ap.error(f"--env wants K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        extra[k] = v
    plan = make_launch_plan(args.hosts.split(","), args.entry,
                            port=args.port,
                            devices_per_host=args.devices_per_host,
                            extra_env=extra or None)
    fmt = {"env": format_env, "ssh": format_ssh,
           "k8s": lambda p: format_k8s(p, args.jobname, args.image),
           "elastic": lambda p: format_elastic(p, args.workdir)}
    try:
        print(fmt[args.format](plan))
    except BrokenPipeError:  # output piped into head/grep that closed early
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
