#!/bin/bash
# Round-5 scripted live-tunnel session (VERDICT r4 next-round #1/#9).
#
# Waits for the TPU tunnel to answer, then runs the queued perf stages in
# priority order, re-checking liveness between stages so a mid-session
# wedge stops cleanly instead of stacking work on a dead tunnel.  Every
# stage appends JSONL to docs/ so partial sessions still leave committed
# evidence.  Safe to re-run: stages that already have a result line in
# their sink are skipped (delete the sink line to re-measure).
#
# Usage: nohup bash tools/r5_live_session.sh > .live_session.log 2>&1 &
cd "$(dirname "$0")/.." || exit 1
LOG() { echo "[$(date -u +%FT%TZ)] $*"; }

alive() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform == 'tpu', d
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
" >/dev/null 2>&1
}

wait_alive() {
  local n=0
  while ! alive; do
    n=$((n+1))
    LOG "tunnel wedged (attempt $n); sleeping 420s"
    echo "wedged $(date -u +%FT%TZ) $n" > .tpu_status
    sleep 420
  done
  echo "alive $(date -u +%FT%TZ)" > .tpu_status
  LOG "tunnel ALIVE"
}

have() { [ -s "$1" ] && grep -q "$2" "$1"; }

HVF=docs/PROBE_r05_hand_vs_framework.jsonl

wait_alive

# Stage 1a: hand-JAX ResNet-50 step (the geometry ceiling).
if have "$HVF" hand_jax; then LOG "skip hand_jax (already captured)"; else
  LOG "stage hand_jax"
  PROBE_VARIANT=hand PROBE_SINK="$HVF" timeout 1500 \
    python tools/resnet_hand_probe.py
  LOG "stage hand_jax rc=$?"
  wait_alive
fi

# Stage 1b: framework ResNet-50 step at identical shapes.
if have "$HVF" framework; then LOG "skip framework (already captured)"; else
  LOG "stage framework"
  PROBE_VARIANT=framework PROBE_SINK="$HVF" timeout 1500 \
    python tools/resnet_hand_probe.py
  LOG "stage framework rc=$?"
  wait_alive
fi

# Stage 1c: per-stage ResNet geometry probe (which stage loses MFU).
if have docs/PROBE_r05_stages.jsonl head; then LOG "skip stage probe"; else
  LOG "stage resnet stages"
  PROBE_SINK=docs/PROBE_r05_stages.jsonl timeout 1500 \
    python tools/resnet_stage_probe.py
  LOG "stage resnet stages rc=$?"
  wait_alive
fi

# Stage 2: does Mosaic/Pallas compile over the tunnel?
if [ -s docs/PROBE_r05_flash.jsonl ]; then LOG "skip flash probe"; else
  LOG "stage flash"
  timeout 900 python tools/flash_probe.py 2>/dev/null \
    | grep '^{' >> docs/PROBE_r05_flash.jsonl
  LOG "stage flash rc=$?"
  wait_alive
fi

# Stage 3: run_steps dispatch-amortization re-measure on live hardware
# (VERDICT r4 next-round #9): default dispatch vs K=8 scan.
if [ -s docs/PROBE_r05_run_steps.jsonl ]; then LOG "skip run_steps"; else
  LOG "stage run_steps (BENCH_SPD=8 resnet)"
  D=$(BENCH_MODEL=resnet timeout 1500 python bench.py 2>/dev/null | tail -1)
  S=$(BENCH_MODEL=resnet BENCH_SPD=8 timeout 1500 python bench.py 2>/dev/null | tail -1)
  { echo "{\"mode\": \"default\", \"line\": ${D:-null}}"
    echo "{\"mode\": \"spd8\", \"line\": ${S:-null}}" ; } \
    >> docs/PROBE_r05_run_steps.jsonl
  LOG "stage run_steps done"
  wait_alive
fi

# Stage 4: jitted beam decode on silicon, fp32 and int8 weights
# (VERDICT r4 next-round #7: decode+int8 composition numbers).
if [ -s docs/PROBE_r05_decode.jsonl ]; then LOG "skip decode"; else
  LOG "stage decode (jit, then +int8)"
  DJ=$(BENCH_MODEL=decode timeout 1200 python bench.py 2>/dev/null | tail -1)
  DI=$(BENCH_MODEL=decode BENCH_INT8=1 timeout 1200 python bench.py 2>/dev/null | tail -1)
  { echo "{\"mode\": \"decode_jit\", \"line\": ${DJ:-null}}"
    echo "{\"mode\": \"decode_jit_int8\", \"line\": ${DI:-null}}" ; } \
    >> docs/PROBE_r05_decode.jsonl
  LOG "stage decode done"
  wait_alive
fi

# Stage 5: full default bench capture (resnet + transformer) for the log.
LOG "stage bench (full default)"
timeout 2400 python bench.py 2>/dev/null | tail -1 >> docs/BENCH_live_r05.jsonl
LOG "bench done rc=$?"
wait_alive

# Stage 6: if the flash probe compiled clean (every stage ok — the probe
# stops at its first failure, so any ok:false line means broken), capture
# the transformer with the Pallas path enabled for comparison.
if grep -q '"ok": true' docs/PROBE_r05_flash.jsonl 2>/dev/null \
   && ! grep -q '"ok": false' docs/PROBE_r05_flash.jsonl; then
  LOG "stage bench (BENCH_FLASH=1 transformer)"
  F=$(BENCH_MODEL=transformer BENCH_FLASH=1 timeout 1500 python bench.py 2>/dev/null | tail -1)
  echo "{\"mode\": \"transformer_flash\", \"line\": ${F:-null}}" \
    >> docs/BENCH_live_r05.jsonl
fi
LOG "session complete"
