#!/usr/bin/env python
"""Open-loop load generator for the serving engine (docs/SERVING.md).

Open-loop means requests are fired on a fixed arrival schedule derived
from --qps, NOT when the previous response returns — the generator never
slows down to match the server, so queueing/shedding behavior under a
genuinely offered load is visible (a closed-loop generator would hide
overload by self-throttling, the classic coordinated-omission mistake).

Builds a mnist-sized MLP in-process (or serves --model-dir), saves it,
stands up a ServingEngine, warms the buckets, offers load for --duration
seconds, and emits ONE BENCH-style JSON line on stdout:

    {"metric": "serving_mlp784_openloop_cpu", "value": <qps>,
     "unit": "req/s", "offered_qps": ..., "p50_ms": ..., "p95_ms": ...,
     "p99_ms": ..., "mean_batch_occupancy": ..., "shed": ..., ...}

Modes:
    --smoke     2-second CPU sanity pass for CI (exit 0 + valid JSON is
                the contract; tests/tier-2 can parse the line)
    --decode    continuous-batching decode workload (ISSUE 15): open-loop
                generation requests with a mixed short/long token-budget
                distribution through the DecodeEngine; the BENCH line
                reports tokens/s, TTFT p50/p99, inter-token p99 and the
                executable count (fixed-set invariant:
                compiles_after_warmup must be 0)
    --router    serving-fleet workload (ISSUE 17): --models x --replicas
                decode replicas behind one router; the BENCH line
                reports per-model qps/p50/p99/shed plus the
                ready-replica-count trajectory sampled through the run
    default     --duration/--qps as given; --device TPU serves from the
                accelerator when one is attached
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _build_and_save(model_dir: str, hidden: int = 64) -> None:
    """Train-free mnist-sized MLP (784 -> hidden -> 10 softmax)."""
    import paddle_tpu.fluid as fluid

    fluid.default_main_program().random_seed = 17
    fluid.default_startup_program().random_seed = 17
    img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    h = fluid.layers.fc(img, size=hidden, act="relu")
    pred = fluid.layers.fc(h, size=10, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(model_dir, ["img"], [pred], exe)


def run_bench(args) -> dict:
    import numpy as np

    from paddle_tpu.inference import AnalysisConfig, PaddleTensor
    from paddle_tpu.serving import (EngineOverloaded, ServingConfig,
                                    create_serving_engine)

    model_dir = args.model_dir
    if not model_dir:
        model_dir = tempfile.mkdtemp(prefix="bench_serving_")
        _build_and_save(model_dir)

    cfg = AnalysisConfig(model_dir=model_dir,
                         use_tpu=(args.device.upper() == "TPU"))
    eng = create_serving_engine(cfg, ServingConfig(
        max_batch_size=args.max_batch_size,
        max_wait_ms=args.max_wait_ms,
        max_queue_depth=args.queue_depth))
    sample = [PaddleTensor(name=n, data=r) for n, r in zip(
        eng._feed_names, _sample_rows(eng))] if args.model_dir else None
    eng.warmup(sample_inputs=sample)
    warm = eng.metrics.snapshot()

    rng = np.random.RandomState(0)
    # pre-generate a pool of request payloads so the generator's hot loop
    # is submit-only (payload synthesis must not gate the offered rate)
    pool = [[PaddleTensor(name=eng._feed_names[0],
                          data=rng.normal(size=(1, 784)).astype(np.float32))]
            for _ in range(256)] if not args.model_dir else \
           [sample for _ in range(256)]

    results = {"ok": 0, "shed": 0, "err": 0}
    rlock = threading.Lock()

    def on_done(fut):
        with rlock:
            if fut.exception() is None:
                results["ok"] += 1
            else:
                results["err"] += 1

    period = 1.0 / args.qps
    t_end = time.perf_counter() + args.duration
    next_fire = time.perf_counter()
    sent = 0
    while True:
        now = time.perf_counter()
        if now >= t_end:
            break
        if now < next_fire:
            time.sleep(min(next_fire - now, 0.002))
            continue
        # open loop: the schedule advances by the period even when we fell
        # behind, so the offered rate stays honest
        next_fire += period
        try:
            eng.submit(pool[sent % len(pool)]).add_done_callback(on_done)
            sent += 1
        except EngineOverloaded:
            with rlock:
                results["shed"] += 1
    eng.drain(timeout_s=60.0)
    snap = eng.metrics.snapshot()
    eng.shutdown()

    # windowed interval rates (warm-snapshot -> final-snapshot diff): the
    # cumulative snapshot qps includes warmup dead time and decays toward
    # the lifetime mean; the window is the actual serving interval
    from paddle_tpu.serving import ServingMetrics

    win = ServingMetrics.window(warm, snap)
    out = {
        "metric": f"serving_mlp784_openloop_{args.device.lower()}",
        "value": win["qps"],
        "unit": "req/s",
        "offered_qps": args.qps,
        "duration_s": args.duration,
        "window_s": win["interval_s"],
        "sent": sent,
        "completed": results["ok"],
        "shed": results["shed"] + win["shed"],
        "errors": results["err"],
        "p50_ms": snap["p50_ms"],
        "p95_ms": snap["p95_ms"],
        "p99_ms": snap["p99_ms"],
        "mean_batch_occupancy": win["mean_batch_occupancy"],
        "dispatches": win["dispatches"],
        "dispatch_rate": win["dispatch_rate"],
        "bucket_compiles": snap["bucket_compiles"],
        "compiles_after_warmup":
            snap["bucket_compiles"] - warm["bucket_compiles"],
        "max_batch_size": args.max_batch_size,
        "max_wait_ms": args.max_wait_ms,
        "queue_depth": args.queue_depth,
        "smoke": bool(args.smoke),
    }
    return out


def _sample_rows(eng):
    """Zero rows from the model's own feed shapes (for --model-dir)."""
    return list(eng._zero_rows().values())


def run_decode_bench(args) -> dict:
    """Open-loop mixed-length decode workload through the DecodeEngine.

    Arrivals fire on the --qps schedule; each request draws a token
    budget from a bimodal distribution (80% short --short-new, 20% long
    --long-new) — the convoy-forming mix iteration-level scheduling
    exists for.  Reported rates come from a warm->final
    ``ServingMetrics.window`` so warmup dead time never dilutes them."""
    import numpy as np

    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (DecodeConfig, DecodeEngine,
                                    EngineOverloaded, ServingMetrics)

    paged = None if args.paged < 0 else bool(args.paged)
    if getattr(args, "prefix_share", -1) >= 0:
        import os as _os

        _os.environ["PADDLE_SERVE_PREFIX_SHARE"] = str(args.prefix_share)
    model = transformer.DecodeModel(
        cfg=transformer.decode_lm_config(),
        max_slots=args.slots, max_len=args.max_len,
        prefill_buckets=[4, 8], paged=paged,
        page_size=args.page_size, num_pages=args.num_pages)
    # --spec k arms speculative decoding (ISSUE 20).  --draft-layers
    # defaults to 0 = full-depth self-draft: the acceptance ceiling
    # (rate 1.0), so the line measures the draft+verify machinery's
    # throughput headroom; pass a small n for a realistic cheap draft.
    spec_k = int(getattr(args, "spec", 0) or 0)
    eng = DecodeEngine(model, DecodeConfig(
        max_queue_depth=args.queue_depth,
        spec=spec_k if spec_k > 0 else None,
        spec_draft_layers=getattr(args, "draft_layers", None)))
    eng.warmup()
    warm = eng.metrics.snapshot()
    # dense KV footprint for the equal-HBM comparison in either mode
    kv_dense_bytes = (model.max_slots * model.max_len
                      * model.cfg.d_model * 4 * 2 * model.cfg.n_layer)

    rng = np.random.RandomState(0)
    if args.shared_prefix:
        # every prompt shares one full first page (page-size tokens of
        # common prefix + one distinct tail token): under prefix sharing
        # concurrent admissions hit the resident page and, with the tail
        # on the private page boundary, skip their prefill outright
        ps = model.page_size if getattr(model, "paged", False) else 4
        base = [int(t) for t in rng.randint(2, model.vocab_size - 1,
                                            size=ps)]
        pool = [base + [int(t)]
                for t in rng.randint(2, model.vocab_size - 1, size=64)]
    elif spec_k > 0:
        # repetitive prompts: the draftable load speculation pays on
        pool = [[int(t)] * 3
                for t in rng.randint(2, model.vocab_size - 1, size=64)]
    else:
        pool = [[int(t) for t in rng.randint(2, model.vocab_size - 1,
                                             size=3)]
                for _ in range(64)]
    budgets = [args.long_new if rng.random_sample() < 0.2
               else args.short_new for _ in range(256)]

    # --swaps N: hot-swap N fresh serials THROUGH the open-loop window
    # (ISSUE 16 acceptance: zero shed, p99 inside the no-swap band).
    # The registry's own background watcher does the swapping; the
    # arrival loop only commits serials on schedule, like a trainer
    # publishing checkpoints mid-traffic.
    reg = None
    swap_serials = []
    n_swaps = int(getattr(args, "swaps", 0) or 0)
    if n_swaps > 0:
        import tempfile

        from paddle_tpu.serving import ModelRegistry, write_weights_serial

        swap_root = tempfile.mkdtemp(prefix="bench_swap_")
        w0 = eng.snapshot_weights(model.weight_names())
        prng = np.random.RandomState(1)

        def _serial_weights():
            return {n: (np.asarray(a)
                        + 0.01 * prng.normal(size=np.shape(a))
                        ).astype(np.asarray(a).dtype)
                    if np.issubdtype(np.asarray(a).dtype, np.floating)
                    else np.array(a, copy=True)
                    for n, a in w0.items()}

        reg = ModelRegistry(eng, swap_root, policy=args.swap_policy,
                            canary_requests=0, serial=0)
        reg.start(poll_s=0.1)
        _write_serial = write_weights_serial

    results = {"ok": 0, "shed": 0, "err": 0}
    rlock = threading.Lock()

    def on_done(fut):
        with rlock:
            if fut.exception() is None:
                results["ok"] += 1
            else:
                results["err"] += 1

    period = 1.0 / args.qps
    t_start = time.perf_counter()
    t_end = t_start + args.duration
    next_fire = t_start
    # commit serials at evenly spaced points INSIDE the window so every
    # swap happens under live load, none in the drain tail
    commit_at = [t_start + args.duration * (i + 1) / (n_swaps + 1)
                 for i in range(n_swaps)]
    sent = 0
    kv_peak_pages = 0
    peak_active = 0
    while True:
        now = time.perf_counter()
        peak_active = max(peak_active, eng._n_active)
        if eng._pool is not None:
            kv_peak_pages = max(kv_peak_pages, eng._pool.pages_live)
        if now >= t_end:
            break
        if commit_at and now >= commit_at[0]:
            commit_at.pop(0)
            serial = len(swap_serials) + 1
            _write_serial(swap_root, serial, _serial_weights())
            swap_serials.append(serial)
        if now < next_fire:
            time.sleep(min(next_fire - now, 0.002))
            continue
        next_fire += period
        try:
            eng.submit(pool[sent % len(pool)],
                       budgets[sent % len(budgets)]) \
                .add_done_callback(on_done)
            sent += 1
        except EngineOverloaded:
            with rlock:
                results["shed"] += 1
    if reg is not None:
        # give the watcher one beat to ingest the last committed serial,
        # then stop it before the drain (no swaps against an empty engine)
        deadline = time.perf_counter() + 5.0
        while swap_serials and reg.serial < swap_serials[-1] \
                and time.perf_counter() < deadline:
            time.sleep(0.02)
        reg.stop()
    eng.drain(timeout_s=60.0)
    snap = eng.metrics.snapshot()
    executables = eng.executables()
    spec = eng._spec
    eng.shutdown()

    win = ServingMetrics.window(warm, snap)
    spec_ticks_d = snap["spec_ticks"] - warm["spec_ticks"]
    drafted_d = snap["spec_draft_tokens"] - warm["spec_draft_tokens"]
    accepted_d = snap["spec_accepted_tokens"] - warm["spec_accepted_tokens"]
    ticks_d = snap["decode_ticks"] - warm["decode_ticks"]
    tokens_d = snap["tokens_generated"] - warm["tokens_generated"]
    return {
        "metric": f"serving_decode_openloop_{args.device.lower()}",
        "value": win["tokens_per_s"],
        "unit": "tokens/s",
        "offered_qps": args.qps,
        "duration_s": args.duration,
        "window_s": win["interval_s"],
        "sent": sent,
        "completed": results["ok"],
        "shed": results["shed"] + win["shed"],
        "errors": results["err"],
        "qps": win["qps"],
        "tick_rate": win["tick_rate"],
        "ttft_p50_ms": snap["ttft_p50_ms"],
        "ttft_p99_ms": snap["ttft_p99_ms"],
        "intertoken_p99_ms": snap["intertoken_p99_ms"],
        "p50_ms": snap["p50_ms"],
        "p99_ms": snap["p99_ms"],
        "tokens_generated": snap["tokens_generated"],
        "executables": executables,
        "compiles_after_warmup":
            snap["bucket_compiles"] - warm["bucket_compiles"],
        "slots": args.slots,
        "max_len": args.max_len,
        "short_new": args.short_new,
        "long_new": args.long_new,
        # paged KV cache (ISSUE 19): device KV footprint in both modes
        # (kvpool_hbm_bytes = the page pool incl. trash page; dense =
        # the [slots, max_len] caches) so two BENCH lines prove the
        # more-slots-at-equal-HBM claim, plus the sharing counters
        "paged": bool(getattr(model, "paged", False)),
        "page_size": model.page_size if getattr(model, "paged", False)
        else None,
        "num_pages": model.num_pages if getattr(model, "paged", False)
        else None,
        "kvpool_hbm_bytes": ((model.num_pages + 1) * model.page_size
                             * model.cfg.d_model * 4 * 2
                             * model.cfg.n_layer
                             if getattr(model, "paged", False) else None),
        "kvpool_peak_live_pages": (kv_peak_pages
                                   if getattr(model, "paged", False)
                                   else None),
        "kv_dense_bytes": kv_dense_bytes,
        "peak_active_slots": peak_active,
        "prefix_hits": snap["prefix_hits"] - warm["prefix_hits"],
        "prefill_skips": snap["prefill_skips"] - warm["prefill_skips"],
        "page_requeues": snap["page_requeues"] - warm["page_requeues"],
        "prefills": snap["prefills"] - warm["prefills"],
        "shared_prefix": bool(args.shared_prefix),
        "swaps": snap["model_swaps"] - warm["model_swaps"],
        "swap_policy": args.swap_policy if n_swaps > 0 else None,
        # speculative decoding (ISSUE 20): window acceptance, committed
        # tokens per engine tick (all slots; plain decode caps at one
        # per ACTIVE slot per tick, speculation at k+1), and the
        # per-spec-tick draft/verify cost split
        "spec_k": spec_k,
        "draft_layers": (spec.draft.model.cfg.n_layer
                         if spec is not None else None),
        "acceptance_rate": (round(accepted_d / drafted_d, 4)
                            if drafted_d else None),
        "tokens_per_tick": (round(tokens_d / ticks_d, 4)
                            if ticks_d else None),
        "spec_fallbacks": snap["spec_fallbacks"] - warm["spec_fallbacks"],
        "draft_ms": (round(spec.draft_s / spec_ticks_d * 1e3, 3)
                     if spec is not None and spec_ticks_d else None),
        "verify_ms": (round(spec.verify_s / spec_ticks_d * 1e3, 3)
                      if spec is not None and spec_ticks_d else None),
        "smoke": bool(args.smoke),
    }


def run_router_bench(args) -> dict:
    """Open-loop multi-model load through a ServingFleet (ISSUE 17).

    ``--models M x --replicas R`` tiny decode models behind one router;
    arrivals round-robin the models on the --qps schedule.  Latencies
    are measured end to end at the CLIENT (router queueing + failover
    included), per model; a sampler thread records the ready-replica
    count per model every 250 ms so the BENCH line carries the fleet's
    scaling trajectory, not just its endpoint."""
    import numpy as np

    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (AutoscalePolicy, DecodeEngine,
                                    EngineOverloaded, ServingFleet)

    # the shared compile store is what makes an R-replica fleet warm in
    # one compile's time; give the bench one even when the env has none
    if not os.environ.get("PADDLE_COMPILE_CACHE_DIR"):
        os.environ["PADDLE_COMPILE_CACHE_DIR"] = \
            tempfile.mkdtemp(prefix="bench_router_cache_")

    models = [f"m{i}" for i in range(args.models)]

    def factory(seed):
        def make(labels):
            model = transformer.DecodeModel(
                cfg=transformer.decode_lm_config(), max_slots=args.slots,
                max_len=args.max_len, prefill_buckets=[4, 8], seed=seed)
            return DecodeEngine(model, metrics_labels=labels)
        return make

    fleet = ServingFleet(
        {m: factory(11 + 2 * i) for i, m in enumerate(models)},
        replicas=args.replicas,
        hb_dir=tempfile.mkdtemp(prefix="bench_router_hb_"),
        # the bench measures the offered load, not idle-downscale churn:
        # pin the floor at the starting shape, let pressure scale out
        policy=AutoscalePolicy(min_replicas=args.replicas))
    t_warm = time.perf_counter()
    fleet.start(wait_ready_s=300.0)
    warm_s = time.perf_counter() - t_warm

    rng = np.random.RandomState(0)
    pool = [[int(t) for t in rng.randint(2, 60, size=3)]
            for _ in range(64)]
    budgets = [args.long_new if rng.random_sample() < 0.2
               else args.short_new for _ in range(256)]

    lat = {m: [] for m in models}       # client-side e2e seconds
    results = {m: {"ok": 0, "shed": 0, "err": 0} for m in models}
    rlock = threading.Lock()

    def on_done(model, t0):
        def cb(fut):
            dt = time.perf_counter() - t0
            with rlock:
                if fut.exception() is None:
                    results[model]["ok"] += 1
                    lat[model].append(dt)
                else:
                    results[model]["err"] += 1
        return cb

    trajectory = []
    stop_sampler = threading.Event()

    def sample():
        t0 = time.perf_counter()
        while not stop_sampler.wait(0.25):
            st = fleet.status()
            trajectory.append(
                {"t_s": round(time.perf_counter() - t0, 2),
                 **{m: st["models"][m]["ready"] for m in models}})

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()

    period = 1.0 / args.qps
    t_start = time.perf_counter()
    t_end = t_start + args.duration
    next_fire = t_start
    sent = 0
    while True:
        now = time.perf_counter()
        if now >= t_end:
            break
        if now < next_fire:
            time.sleep(min(next_fire - now, 0.002))
            continue
        next_fire += period
        model = models[sent % len(models)]
        try:
            fleet.submit(model, pool[sent % len(pool)],
                         budgets[sent % len(budgets)]) \
                .add_done_callback(on_done(model, time.perf_counter()))
        except EngineOverloaded:
            with rlock:
                results[model]["shed"] += 1
        sent += 1
    fleet.router.drain(timeout_s=120.0)
    stop_sampler.set()
    sampler.join(timeout=5.0)
    window_s = time.perf_counter() - t_start
    status = fleet.status()
    fleet.shutdown(timeout_s=60.0)

    def pct(vals, q):
        return round(float(np.percentile(vals, q)) * 1e3, 3) \
            if vals else None

    per_model = {}
    for m in models:
        r = results[m]
        per_model[m] = {
            "completed": r["ok"],
            "qps": round(r["ok"] / window_s, 3),
            "p50_ms": pct(lat[m], 50),
            "p99_ms": pct(lat[m], 99),
            "shed": r["shed"] + status["models"][m]["shed"],
            "errors": r["err"],
            "replicas_final": status["models"][m]["ready"],
            "dispatched": status["models"][m]["dispatched"],
        }
    completed = sum(r["ok"] for r in results.values())
    return {
        "metric": f"serving_fleet_openloop_{args.device.lower()}",
        "value": round(completed / window_s, 3),
        "unit": "req/s",
        "offered_qps": args.qps,
        "duration_s": args.duration,
        "window_s": round(window_s, 3),
        "warm_s": round(warm_s, 3),
        "sent": sent,
        "completed": completed,
        "shed": sum(v["shed"] for v in per_model.values()),
        "errors": sum(r["err"] for r in results.values()),
        "p50_ms": pct([d for v in lat.values() for d in v], 50),
        "p99_ms": pct([d for v in lat.values() for d in v], 99),
        "models": per_model,
        "replica_trajectory": trajectory,
        "n_models": args.models,
        "replicas": args.replicas,
        "slots": args.slots,
        "max_len": args.max_len,
        "short_new": args.short_new,
        "long_new": args.long_new,
        "smoke": bool(args.smoke),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model-dir", default="",
                   help="serve this saved inference model instead of the "
                        "built-in mnist-sized MLP")
    p.add_argument("--device", default="CPU", choices=["CPU", "TPU",
                                                       "cpu", "tpu"])
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds of offered load")
    p.add_argument("--qps", type=float, default=500.0,
                   help="open-loop offered request rate")
    p.add_argument("--max-batch-size", type=int, default=16)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--queue-depth", type=int, default=512)
    p.add_argument("--decode", action="store_true",
                   help="continuous-batching decode workload (DecodeEngine "
                        "with a mixed short/long token-budget mix)")
    p.add_argument("--slots", type=int, default=8,
                   help="decode slots (concurrent KV-cache streams)")
    p.add_argument("--max-len", type=int, default=128,
                   help="decode KV-cache capacity per slot")
    p.add_argument("--short-new", type=int, default=8,
                   help="short-request token budget (80%% of arrivals)")
    p.add_argument("--long-new", type=int, default=64,
                   help="long-request token budget (20%% of arrivals)")
    p.add_argument("--paged", type=int, default=-1, choices=[-1, 0, 1],
                   help="paged KV cache for --decode: 1 on, 0 dense, "
                        "-1 defer to PADDLE_SERVE_PAGED (ISSUE 19)")
    p.add_argument("--page-size", type=int, default=None,
                   help="tokens per KV page (--paged; default "
                        "PADDLE_SERVE_PAGE_SIZE)")
    p.add_argument("--num-pages", type=int, default=None,
                   help="device page-pool size (--paged; 0/unset = "
                        "max_slots * max_len / page_size).  Size this to "
                        "a SMALLER dense engine's kv_cache_bytes to "
                        "measure more slots at equal HBM")
    p.add_argument("--prefix-share", type=int, default=-1,
                   choices=[-1, 0, 1],
                   help="prefix sharing for --paged (default "
                        "PADDLE_SERVE_PREFIX_SHARE)")
    p.add_argument("--shared-prefix", action="store_true",
                   help="decode workload where every prompt shares one "
                        "full first page (drives prefix_hits / "
                        "prefill_skips)")
    p.add_argument("--spec", type=int, default=0,
                   help="speculative decoding: k draft tokens per tick "
                        "through a self-drafted verify dispatch "
                        "(ISSUE 20; 0 = off)")
    p.add_argument("--draft-layers", type=int, default=None,
                   help="self-draft depth for --spec (default "
                        "PADDLE_SERVE_SPEC_DRAFT_LAYERS; 0 = full-depth "
                        "self-draft, the acceptance-1.0 throughput "
                        "ceiling)")
    p.add_argument("--swaps", type=int, default=0,
                   help="hot-swap this many fresh serials through the "
                        "decode window (registry watcher; ISSUE 16)")
    p.add_argument("--swap-policy", default="immediate",
                   choices=["immediate", "drain"],
                   help="in-flight policy for --swaps")
    p.add_argument("--router", action="store_true",
                   help="multi-model fleet workload: --models x "
                        "--replicas decode replicas behind one router "
                        "(per-model qps/p50/p99/shed + the "
                        "replica-count trajectory)")
    p.add_argument("--models", type=int, default=2,
                   help="distinct models behind the router (--router)")
    p.add_argument("--replicas", type=int, default=2,
                   help="starting replicas per model (--router)")
    p.add_argument("--smoke", action="store_true",
                   help="2-second CPU sanity pass for CI")
    args = p.parse_args(argv)
    if args.smoke:
        args.duration = 2.0
        args.qps = min(args.qps, 40.0 if args.decode or args.router
                       else 200.0)
        args.device = "CPU"
        if args.decode or args.router:
            args.slots = min(args.slots, 4)
            args.max_len = min(args.max_len, 64)
            args.long_new = min(args.long_new, 32)
        if args.router:
            args.models = min(args.models, 2)
            args.replicas = min(args.replicas, 2)

    out = run_router_bench(args) if args.router \
        else run_decode_bench(args) if args.decode else run_bench(args)
    print(json.dumps(out))
    # smoke contract: the pass fails loudly if nothing was actually served
    if args.smoke and (out["completed"] == 0 or out["p50_ms"] is None):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
