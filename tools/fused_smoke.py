"""Fused-kernel smoke (CPU interpret mode, < 5 s).

The CI oracle for the Pallas fused-kernel layer (ISSUE 12): a GUARDED
16-step training window through the streaming softmax-cross-entropy and
the fused adam sweep must

 - train all 16 steps with ``PADDLE_TPU_FUSED=1`` (interpret mode on the
   CPU mesh) and finish with losses matching the unfused XLA lowering
   within 1e-6,
 - leave nonzero ``ops.fused.softmax_xent`` / ``ops.fused.adam`` dispatch
   counters in the always-on registry, and
 - with the ``PADDLE_TPU_FUSED=0`` kill-switch, restore the EXACT unfused
   lowering: the kill-switch run's losses are bit-identical to the
   baseline unfused run.

Run directly (``python tools/fused_smoke.py``) or from tier-1 via
``tests/test_pallas_fused.py::test_fused_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 16


def _one_run(fused: str, feeds):
    """Fresh program/scope/executor per config (the jit + trace caches key
    on the env knob, but a fresh session keeps the oracle airtight)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    import paddle_tpu.fluid.executor as _executor
    from paddle_tpu.fluid import framework, guardian, unique_name

    os.environ["PADDLE_TPU_FUSED"] = fused
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    _executor._global_scope = _executor.Scope()
    fluid.default_main_program().random_seed = 11
    fluid.default_startup_program().random_seed = 11

    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=16, act="relu")
    logits = fluid.layers.fc(input=h, size=10, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

    guardian.install(guardian.GuardianConfig(policy="skip"))
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        (lv,) = exe.run_steps(fluid.default_main_program(), feed=feeds,
                              fetch_list=[loss], n_steps=N_STEPS,
                              feed_per_step=True)
        guardian.flush()
        gm = guardian.metrics()
    finally:
        guardian.disable()
    return float(np.asarray(lv).reshape(-1)[0]), gm


def main() -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid

    t0 = time.perf_counter()
    prev = os.environ.get("PADDLE_TPU_FUSED")
    rng = np.random.RandomState(3)
    feeds = {"x": rng.normal(size=(N_STEPS, 8, 16)).astype(np.float32),
             "label": rng.randint(0, 10, size=(N_STEPS, 8, 1))
             .astype(np.int64)}
    try:
        c0 = dict(fluid.profiler.counters())
        base, gm_base = _one_run("0", feeds)     # unfused baseline
        fused, gm_fused = _one_run("1", feeds)   # fused kernels
        kill, _ = _one_run("0", feeds)           # kill-switch restore
        c1 = fluid.profiler.counters()
    finally:
        # restore env for in-process callers (the tier-1 test imports us)
        if prev is None:
            os.environ.pop("PADDLE_TPU_FUSED", None)
        else:
            os.environ["PADDLE_TPU_FUSED"] = prev

    def delta(name):
        return c1.get(name, 0) - c0.get(name, 0)

    xent = delta("ops.fused.softmax_xent")
    adam = delta("ops.fused.adam")
    report = {
        "ok": bool(
            np.isfinite(base) and np.isfinite(fused)
            and abs(fused - base) < 1e-6       # fused ≡ unfused semantics
            and kill == base                   # kill-switch is EXACT
            and xent > 0 and adam > 0
            and gm_base.get("steps") == N_STEPS
            and gm_fused.get("steps") == N_STEPS
            and gm_fused.get("trips", 0) == 0),
        "loss_unfused": base,
        "loss_fused": fused,
        "loss_killswitch": kill,
        "fused_vs_unfused_diff": abs(fused - base),
        "killswitch_bitwise": kill == base,
        "ops_fused_softmax_xent": int(xent),
        "ops_fused_adam": int(adam),
        "guardian_steps": gm_fused.get("steps"),
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
