"""Goodput-accounting smoke (CPU, < 5 s) — the ISSUE 13 CI oracle.

A 16-step guarded training window is fed through a checkpointable data
pipeline with ONE injected 150 ms input stall (``PADDLE_FAULT_DATA_STALL_MS``
at a fixed source cursor), under a temp observe dir:

 - the live accumulator must book nonzero ``data_wait``-state time and a
   goodput fraction strictly inside (0, 1) (the stall and the compile
   guarantee wall-clock the device did not train);
 - ``goodput.seconds{state=...}`` counters and a forced ``goodput.report``
   event must exist;
 - the ``python -m paddle_tpu.observe goodput`` CLI must re-derive a
   ledger FROM THE PERSISTED STREAM ALONE whose per-worker states sum to
   its wall-clock (coverage == 1) with nonzero device AND stall time.

Run directly (``python tools/goodput_smoke.py``) or from tier-1 via
``tests/test_goodput.py::test_goodput_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_STEPS = 16
BATCH = 8
STALL_MS = 150.0
STALL_AT = 4  # source sample cursor the one-shot stall fires at


def main() -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import data, observe
    from paddle_tpu.fluid import fault
    from paddle_tpu.observe import goodput

    t_start = time.perf_counter()
    root = tempfile.mkdtemp(prefix="goodput_smoke_")
    report = {"ok": False, "root": root}
    try:
        observe.configure(root, flush_s=60.0)
        fault.install(fault.FaultPlan(data_stall_ms=STALL_MS,
                                      data_stall_at=STALL_AT))

        prog, startup = fluid.Program(), fluid.Program()
        prog.random_seed = startup.random_seed = 11
        with fluid.program_guard(prog, startup), fluid.unique_name.guard():
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=16, act="relu")
            pred = fluid.layers.fc(input=h, size=1, act=None)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(
                loss, startup_program=startup)

        rng = np.random.RandomState(3)

        def reader():
            for _ in range(N_STEPS * BATCH):
                yield (rng.normal(size=(8,)).astype(np.float32),
                       rng.normal(size=(1,)).astype(np.float32))

        pipe = data.from_reader(reader).batch(BATCH)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            # pull the whole window through the instrumented iterator so
            # every batch's wait (incl. the injected stall) is accounted,
            # then run all 16 steps as ONE dispatch
            feeds = []
            for batch in data.timed(pipe()):
                feeds.append(
                    {"x": np.stack([s[0] for s in batch]),
                     "y": np.stack([s[1] for s in batch])})
                if len(feeds) == N_STEPS:
                    break
            window = {k: np.stack([f[k] for f in feeds])
                      for k in feeds[0]}
            (lv,) = exe.run_steps(prog, feed=window, fetch_list=[loss],
                                  n_steps=N_STEPS, feed_per_step=True)
        report["last_loss"] = float(np.asarray(lv).reshape(-1)[0])
        goodput.report(force=True)

        acc = goodput.get_accumulator()
        snap = acc.snapshot() if acc is not None else {}
        report["live_states"] = snap.get("states", {})
        report["live_fraction"] = snap.get("fraction")
        report["live_ok"] = bool(
            snap
            and snap["states"]["data_wait"] >= STALL_MS / 1e3 * 0.9
            and snap["states"]["device"] > 0.0
            and 0.0 < snap["fraction"] < 1.0)
        flat = observe.registry().flat()
        report["counter_ok"] = \
            flat.get('goodput.seconds{state="data_wait"}', 0.0) > 0.0
        # flush the sink so the subprocess CLI sees the persisted stream
        sink = observe.get_sink()
        if sink is not None:
            sink.flush()

        # -- CLI round-trip: ledger re-derived from the files alone
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observe", "goodput",
             "--dir", root],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        report["cli_rc"] = out.returncode
        ledger = json.loads(out.stdout) if out.returncode == 0 else {}
        states = ledger.get("states", {})
        ranks = ledger.get("ranks", {})
        report["ledger_states"] = states
        report["ledger_fraction"] = ledger.get("fraction")
        report["ledger_ok"] = bool(
            out.returncode == 0
            and states.get("device", 0) > 0
            and states.get("data_wait", 0) > 0
            and 0.0 < ledger.get("fraction", 0) < 1.0
            and all(abs(r["coverage"] - 1.0) < 0.05
                    for r in ranks.values()))

        # goodput.report landed in the stream
        from paddle_tpu.observe.fleet import fleet_events

        report["report_events"] = sum(
            1 for r in fleet_events(root)
            if r.get("event") == "goodput.report")
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(report["live_ok"] and report["counter_ok"]
                            and report["ledger_ok"]
                            and report["report_events"] >= 1)
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        try:
            from paddle_tpu import observe as _obs
            from paddle_tpu.fluid import fault as _fault

            _fault.clear()
            _obs.reset()
        except Exception:
            pass
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
