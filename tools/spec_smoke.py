#!/usr/bin/env python
"""Speculative-decoding smoke (CPU, < 10 s) — the ISSUE 20 CI oracle.

A churn workload through a PAGED DecodeEngine with ``spec=k`` armed,
checked five ways:

 - every spec-decoded stream is BITWISE identical to per-request
   sequential greedy decode over the same config/seed (the draft+verify
   tick changes WHEN tokens appear, never WHICH tokens);
 - acceptance is real: ``spec_accepted_tokens / spec_draft_tokens > 0``
   and ``spec_ticks > 0`` (the engine actually speculated);
 - the executable set stays closed: ``executables()`` is flat across
   the whole loaded run after warmup and ``bucket_compiles`` does not
   grow under traffic;
 - the page pool survives speculative grow/rewind churn:
   ``kvpool.pages_leaked == 0`` and ``pages_free`` returns exactly to
   the initial pool size after drain;
 - the ``PADDLE_FAULT_SPEC_DRAFT_POISON`` drill collapses acceptance
   into a ``specdec.fallback`` (``spec_fallbacks > 0``) while the
   poisoned stream STILL decodes bitwise — garbage drafts cost
   throughput, never correctness.

Run directly (``python tools/spec_smoke.py``) or from tier-1 via
``tests/test_specdec.py::test_spec_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SLOTS = 3
MAX_LEN = 32
BUCKETS = [8]  # one bucket: two fewer prefill compiles keeps this <10s
PAGE_SIZE = 4
SPEC_K = 2


def _jobs(vocab):
    import numpy as np

    rng = np.random.RandomState(20)
    lengths = [3, 5, 8, 4, 6, 3]
    news = [6, 5, 7, 4, 6, 8]
    return [([int(t) for t in rng.randint(2, vocab - 1, size=n)], m)
            for n, m in zip(lengths, news)]


def main() -> dict:
    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import DecodeConfig, DecodeEngine

    os.environ["PADDLE_SERVE_SPEC_WINDOW"] = "4"
    t_start = time.perf_counter()
    report = {"ok": False}
    eng = None
    try:
        model = transformer.DecodeModel(
            cfg=transformer.decode_lm_config(), max_slots=SLOTS,
            max_len=MAX_LEN, prefill_buckets=list(BUCKETS),
            paged=True, page_size=PAGE_SIZE)
        eng = DecodeEngine(model, DecodeConfig(spec=SPEC_K,
                                               spec_draft_layers=1))
        pool = eng._pool
        report["spec_k"] = SPEC_K
        report["pages_free_initial"] = pool.pages_free
        eng.warmup()
        exes_after_warmup = eng.executables()
        report["executables_after_warmup"] = exes_after_warmup

        jobs = _jobs(model.vocab_size)
        # the bitwise oracle: per-request sequential greedy decode over
        # the SAME engine/weights (decode_static never speculates)
        sequential = [eng.decode_static([j])[0][0] for j in jobs]

        # churn: twice the slot count in flight forces admit/retire
        # waves, speculative page growth and mid-stream rewinds
        futs = [eng.submit(p, n) for p, n in jobs]
        outs = [f.result(timeout=60) for f in futs]
        report["bitwise_vs_sequential"] = outs == sequential

        snap = eng.metrics.snapshot()
        drafted = snap["spec_draft_tokens"]
        accepted = snap["spec_accepted_tokens"]
        report["spec_ticks"] = snap["spec_ticks"]
        report["acceptance_rate"] = round(accepted / drafted, 4) \
            if drafted else 0.0
        report["executables_flat"] = \
            eng.executables() == exes_after_warmup
        report["bucket_compiles_under_traffic"] = (
            snap["bucket_compiles"] - eng.metrics.counter(
                "warmup_dispatches"))

        # draft-poison drill: garbage drafts from tick 0 — acceptance
        # collapses, the controller trips, the output stays bitwise
        _fault.install(_fault.FaultPlan(spec_draft_poison=0))
        try:
            poisoned = eng.submit(jobs[0][0], jobs[0][1]).result(
                timeout=60)
        finally:
            _fault.clear()
        report["poison_bitwise"] = poisoned == sequential[0]
        report["spec_fallbacks"] = eng.metrics.counter("spec_fallbacks")

        eng.wait_idle(timeout_s=30)
        report["pages_free_after_drain"] = pool.pages_free
        report["pages_leaked"] = pool.pages_leaked
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["bitwise_vs_sequential"]
            and report["poison_bitwise"]
            and report["spec_ticks"] > 0
            and report["acceptance_rate"] > 0
            and report["executables_flat"]
            and report["spec_fallbacks"] > 0
            and report["pages_free_after_drain"]
            == report["pages_free_initial"]
            and report["pages_leaked"] == 0)
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        if eng is not None:
            try:
                eng.shutdown(timeout_s=10)
            except Exception:
                pass
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
