"""Tracing + attribution smoke (CPU, < 5 s).

The CI oracle for the ISSUE 9 span tracer: with an observe dir
configured,

 - a traced 16-step training window produces an ``executor.window`` span
   with ``executor.stage`` / ``executor.dispatch`` / ``executor.observe``
   children sharing one trace id, the ``window.*_ms`` breakdown gauges,
   and a NONZERO ``device.mfu`` gauge (XLA-cost-backed);
 - 8 served requests produce per-request ``serving.request`` spans that
   decompose into queue / batch / dispatch / resolve children;
 - the merged stream round-trips through the chrome-trace exporter as
   ``"ph": "X"`` complete events carrying span ids;
 - ``PADDLE_TRACE=0`` runs the SAME paths and emits ZERO spans (the
   disabled hot path — no device syncs, no extra lowering), with both
   per-window timings reported so overhead is visible in the log.

Run directly (``python tools/trace_smoke.py``) or from tier-1 via
``tests/test_trace.py::test_trace_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_STEPS = 16
N_REQUESTS = 8


def _build_train(fluid):
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)
    return prog, startup, loss


def _run_window(fluid, np, prog, startup, loss, n_windows=1):
    """Run ``n_windows`` fused 16-step windows; returns per-window ms."""
    rng = np.random.RandomState(3)
    feed = {"x": rng.normal(size=(N_STEPS, 8, 8)).astype(np.float32),
            "y": rng.normal(size=(N_STEPS, 8, 1)).astype(np.float32)}
    times = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(n_windows):
            t = time.perf_counter()
            (lv,) = exe.run_steps(prog, feed=feed, fetch_list=[loss],
                                  n_steps=N_STEPS, feed_per_step=True)
            np.asarray(lv)
            times.append((time.perf_counter() - t) * 1e3)
    return times


def main() -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import observe
    from paddle_tpu.observe.export import chrome_trace
    from paddle_tpu.observe.fleet import fleet_events

    t0 = time.perf_counter()
    root = tempfile.mkdtemp(prefix="trace_smoke_")
    report = {"ok": False, "root": root}
    os.environ["PADDLE_TRACE"] = "1"
    observe.configure(root, flush_s=60.0)
    try:
        # -- 1. traced training window ---------------------------------
        prog, startup, loss = _build_train(fluid)
        traced_ms = _run_window(fluid, np, prog, startup, loss,
                                n_windows=2)
        flat = observe.registry().flat()
        report["mfu"] = flat.get("device.mfu")
        report["mfu_nonzero"] = bool(flat.get("device.mfu"))
        report["breakdown_gauges"] = all(
            f"window.{k}_ms" in flat
            for k in ("host", "stage", "device", "observe"))

        # -- 2. traced serving requests --------------------------------
        from paddle_tpu.inference import (AnalysisConfig, PaddleTensor)
        from paddle_tpu.serving import ServingConfig, create_serving_engine

        model_dir = os.path.join(root, "model")
        with fluid.scope_guard(fluid.Scope()):
            iprog, istartup = fluid.Program(), fluid.Program()
            with fluid.program_guard(iprog, istartup), \
                    fluid.unique_name.guard():
                img = fluid.layers.data(name="img", shape=[16],
                                        dtype="float32")
                out = fluid.layers.fc(input=img, size=4, act="softmax")
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(istartup)
            fluid.io.save_inference_model(model_dir, ["img"], [out], exe,
                                          main_program=iprog)
        eng = create_serving_engine(
            AnalysisConfig(model_dir=model_dir, use_tpu=False),
            ServingConfig(max_batch_size=4, max_wait_ms=1.0))
        try:
            eng.warmup()
            rng = np.random.RandomState(0)
            futs = [eng.submit([PaddleTensor(
                name="img",
                data=rng.normal(size=(1, 16)).astype(np.float32))])
                for _ in range(N_REQUESTS)]
            for f in futs:
                f.result(timeout=30)
        finally:
            eng.shutdown()

        # -- 3. span inventory + chrome round trip ---------------------
        observe.get_sink().flush()
        recs = fleet_events(root)
        spans = [r for r in recs if r.get("span_id")]
        kinds = {}
        for r in spans:
            kinds[r["event"]] = kinds.get(r["event"], 0) + 1
        report["span_kinds"] = kinds
        report["window_spans"] = kinds.get("executor.window", 0) >= 2
        report["window_children"] = all(
            kinds.get(k, 0) >= 2 for k in
            ("executor.stage", "executor.dispatch", "executor.observe"))
        report["request_spans"] = kinds.get("serving.request",
                                            0) == N_REQUESTS
        report["request_children"] = all(
            kinds.get(k, 0) == N_REQUESTS for k in
            ("serving.queue", "serving.dispatch"))
        req = [r for r in spans if r["event"] == "serving.request"]
        q = [r for r in spans if r["event"] == "serving.queue"]
        report["request_decomposes"] = bool(req) and all(
            any(c["parent_span"] == r["span_id"] for c in q) for r in req)
        one_trace = {r["trace_id"] for r in spans
                     if r["event"].startswith("executor.")}
        report["one_trace_per_run"] = len(one_trace) == 1

        trace_json = json.loads(json.dumps(chrome_trace(recs)))
        xs = [e for e in trace_json["traceEvents"] if e.get("ph") == "X"]
        report["chrome_x_events"] = len(xs)
        # duration records only: span-stamped INSTANTS (memory.watermark,
        # memory.profile, cache hits inside a window) render as "i"/"C"
        dur_spans = [r for r in spans if r.get("dur_s") is not None]
        report["chrome_round_trip"] = (
            len(xs) >= len(dur_spans)
            and any(e["args"].get("span_id") for e in xs))

        # -- 4. disabled mode: zero spans, no syncs --------------------
        os.environ["PADDLE_TRACE"] = "0"
        n_spans_before = len(spans)
        prog2, startup2, loss2 = _build_train(fluid)
        untraced_ms = _run_window(fluid, np, prog2, startup2, loss2,
                                  n_windows=2)
        observe.get_sink().flush()
        spans_after = [r for r in fleet_events(root) if r.get("span_id")]
        report["disabled_no_spans"] = len(spans_after) == n_spans_before
        report["window_ms_traced"] = round(traced_ms[-1], 2)
        report["window_ms_untraced"] = round(untraced_ms[-1], 2)

        report["elapsed_s"] = round(time.perf_counter() - t0, 2)
        report["ok"] = all(report[k] for k in (
            "mfu_nonzero", "breakdown_gauges", "window_spans",
            "window_children", "request_spans", "request_children",
            "request_decomposes", "one_trace_per_run",
            "chrome_round_trip", "disabled_no_spans"))
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=8)
    finally:
        os.environ.pop("PADDLE_TRACE", None)
        observe.reset()
        import shutil

        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
