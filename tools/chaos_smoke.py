"""Chaos-engine smoke (CPU, < 10 s) — the ISSUE 18 CI oracle.

Three claims the chaos engine stands on, checked end to end:

 1. **replayability** — two :class:`ChaosSchedule` expansions of the same
    seed produce byte-identical canonical plan JSON (and a different seed
    produces a different plan);
 2. **a real drill passes** — one seeded 2-fault train drill (kill mid-run
    + transient-I/O oracle) executes, resumes, and every applicable
    invariant verdict is PASS, with nonzero ``io.retries`` recovered;
 3. **the verdicts bite** — tampering one persisted artifact (a batch
    digest in the coverage log) and re-evaluating the SAME workdir flips
    the coverage invariant to FAIL (exit path the CLI maps to nonzero).

Run directly (``python tools/chaos_smoke.py``) or from tier-1 via
``tests/test_chaos.py::test_chaos_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    "--xla_force_host_platform_device_count=1 "
    "--xla_cpu_enable_concurrency_optimized_scheduler=false")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCENARIO = "train"
SEED = 3     # samples kill + io_error for the train scenario
FAULTS = 2


def main() -> dict:
    from paddle_tpu.chaos import (ChaosSchedule, SCENARIO_SHAPE,
                                  canonical_json, evaluate_and_report,
                                  run_drill, tamper)

    t_start = time.perf_counter()
    root = tempfile.mkdtemp(prefix="chaos_smoke_")
    report = {"ok": False, "root": root}
    try:
        # 1. replayability: same seed -> identical bytes, new seed -> new
        shape = SCENARIO_SHAPE[SCENARIO]
        a = canonical_json(ChaosSchedule(SCENARIO, SEED, FAULTS,
                                         **shape).plan())
        b = canonical_json(ChaosSchedule(SCENARIO, SEED, FAULTS,
                                         **shape).plan())
        c = canonical_json(ChaosSchedule(SCENARIO, SEED + 1, FAULTS,
                                         **shape).plan())
        report["plan_deterministic"] = bool(a == b)
        report["plan_seed_sensitive"] = bool(a != c)
        keys = sorted(f["key"] for f in json.loads(a)["faults"])
        report["plan_faults"] = keys
        report["plan_has_io_error"] = "io_error" in keys

        # 2. the seeded drill: kill mid-run, resume under the IO oracle
        drill = run_drill(SCENARIO, SEED, FAULTS, root)
        statuses = {v["invariant"]: v["status"]
                    for v in drill["verdicts"]}
        report["verdicts"] = statuses
        report["drill_ok"] = bool(drill["ok"])
        report["retries_recovered"] = bool(
            statuses.get("io_retries_observed") == "PASS")
        report["coverage_pass"] = bool(
            statuses.get("exactly_once_coverage") == "PASS")
        report["bitwise_pass"] = bool(
            statuses.get("bitwise_resume") == "PASS")

        # 3. tamper one artifact, re-judge the SAME workdir -> FAIL
        report["tampered"] = os.path.relpath(tamper(root), root)
        tampered = evaluate_and_report(root)
        t_status = {v["invariant"]: v["status"]
                    for v in tampered["verdicts"]}
        report["tamper_detected"] = bool(
            not tampered["ok"]
            and t_status.get("exactly_once_coverage") == "FAIL")

        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["plan_deterministic"]
            and report["plan_seed_sensitive"]
            and report["plan_has_io_error"]
            and report["drill_ok"]
            and report["retries_recovered"]
            and report["coverage_pass"]
            and report["bitwise_pass"]
            and report["tamper_detected"])
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        try:
            from paddle_tpu import observe as _obs
            from paddle_tpu.fluid import fault as _fault

            _fault.clear()
            _obs.reset()
        except Exception:
            pass
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
