"""Conv/elementwise fusion isolation probe (VERDICT r3 weak #2).

Round-3 isolation measured bare 3x3 conv chains at 56-125 TFLOPs through
the tunnel but conv+relu interleaved at only ~9 TFLOPs — consistent with
ResNet-50 training at ~21 TFLOPs (10.8%% MFU) and suspicious of unfused
elementwise-after-conv.  This probe pins that down with one number per
variant so the fix (layout, flag, or kernel) can be chosen from data:

  conv_chain          N conv layers, no elementwise
  conv_relu           conv -> relu
  conv_bias_relu      conv -> +bias -> relu
  conv_bn_relu        conv -> scale+shift (inference BN) -> relu
  conv_relu_nhwc      same as conv_relu but NHWC layout
  matmul_relu         control: matmul -> relu (MXU path without conv)

Usage:  python tools/conv_fusion_probe.py [N_LAYERS] [HW] [CH] [BATCH] [MM_N]
Emits one JSON line per variant: {"variant", "tflops", "ms_per_step"}.
Each variant runs in a subprocess-friendly way (single process, sequential)
— keep runs short; heavy benchmarking has wedged the tunnel before.
"""

from __future__ import annotations

import os
import sys

import jax

# sitecustomize pre-imports jax pinned to the axon tunnel, so the
# JAX_PLATFORMS env var arrives too late; PROBE_PLATFORM=cpu forces the
# backend in-process (smoke-testing the probe without touching the TPU)
if os.environ.get("PROBE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])

import jax.numpy as jnp
from jax import lax

N_LAYERS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
HW = int(sys.argv[2]) if len(sys.argv) > 2 else 56
CH = int(sys.argv[3]) if len(sys.argv) > 3 else 256
BATCH = int(sys.argv[4]) if len(sys.argv) > 4 else 64
MM_N = int(sys.argv[5]) if len(sys.argv) > 5 else 4096
STEPS = 8


def conv(x, w, dn):
    return lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                    dimension_numbers=dn)


def chain(kind, nhwc=False):
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    key = jax.random.PRNGKey(0)
    shape = (BATCH, HW, HW, CH) if nhwc else (BATCH, CH, HW, HW)
    wshape = (3, 3, CH, CH) if nhwc else (CH, CH, 3, 3)
    x = jax.random.normal(key, shape, jnp.bfloat16) * 0.1
    w = jax.random.normal(key, wshape, jnp.bfloat16) * 0.05
    b = jax.random.normal(key, (CH,), jnp.bfloat16) * 0.1
    bshape = (1, 1, 1, CH) if nhwc else (1, CH, 1, 1)

    def f(x):
        for _ in range(N_LAYERS):
            y = conv(x, w, dn)
            if kind == "conv_relu":
                y = jax.nn.relu(y)
            elif kind == "conv_bias_relu":
                y = jax.nn.relu(y + b.reshape(bshape))
            elif kind == "conv_bn_relu":
                y = jax.nn.relu(y * b.reshape(bshape) + b.reshape(bshape))
            x = y
        return jnp.float32(x).mean()

    return jax.jit(f), x


def matmul_relu():
    key = jax.random.PRNGKey(1)
    n = MM_N
    a = jax.random.normal(key, (n, n), jnp.bfloat16) * 0.05

    def f(x):
        for _ in range(N_LAYERS):
            x = jax.nn.relu(x @ a)
        return jnp.float32(x).mean()

    return jax.jit(f), a


def flops(kind):
    if kind == "matmul_relu":
        return 2 * MM_N ** 3 * N_LAYERS
    return 2 * BATCH * HW * HW * CH * CH * 9 * N_LAYERS


def run(kind, fn, x):
    from _probe_timing import run_timed

    run_timed(kind, fn, (x,), flops(kind), STEPS)


def main():
    for kind in ("conv_chain", "conv_relu", "conv_bias_relu",
                 "conv_bn_relu"):
        fn, x = chain(kind)
        run(kind, fn, x)
    fn, x = chain("conv_relu", nhwc=True)
    run("conv_relu_nhwc", fn, x)
    fn, x = matmul_relu()
    run("matmul_relu", fn, x)


if __name__ == "__main__":
    main()
