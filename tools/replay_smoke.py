"""Guardian record -> trip -> replay round-trip smoke (CPU, < 10 s).

The CI oracle for the flight recorder: train a tiny MLP with a grad-Inf
fault armed, let the ``dump_and_halt`` guardian catch it and write a replay
bundle, then invoke the real ``python -m paddle_tpu.fluid.guardian replay``
CLI in a subprocess and verify the bundle (a) reproduces the recorded loss
bit-for-bit and (b) bisects a first non-finite variable.

Run directly (``python tools/replay_smoke.py``) or from tier-1 via
``tests/test_guardian.py::test_replay_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(workdir=None) -> dict:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import fault, guardian

    workdir = workdir or tempfile.mkdtemp(prefix="replay_smoke_")
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=8, act="relu")
        pred = fluid.layers.fc(input=h, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)

    scope = fluid.Scope()
    guardian.install(guardian.GuardianConfig(
        policy="dump_and_halt", bundle_dir=os.path.join(workdir, "dumps")))
    fault.install(fault.FaultPlan(grad_inf_step=2, mode="raise"))
    bundle = None
    try:
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(3)
            for _ in range(6):
                exe.run(prog, feed={
                    "x": rng.normal(size=(8, 4)).astype(np.float32),
                    "y": rng.normal(size=(8, 1)).astype(np.float32),
                }, fetch_list=[loss])
            guardian.flush()
    except guardian.NumericsTripped as exc:
        bundle = exc.bundle
    finally:
        guardian.disable()
        fault.clear()
    report = {"ok": False, "bundle": bundle, "workdir": workdir}
    if not bundle:
        report["error"] = "guardian did not dump a replay bundle"
        print(json.dumps(report))
        return report

    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.fluid.guardian", "replay", bundle],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    report["cli_returncode"] = proc.returncode
    try:
        cli = json.loads(proc.stdout)
    except ValueError:
        report["error"] = f"replay CLI emitted no JSON: {proc.stderr[-500:]}"
        print(json.dumps(report))
        return report
    report["replay"] = cli
    report["ok"] = (proc.returncode == 0 and cli.get("bitwise_match")
                    and cli.get("first_nonfinite") is not None)
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
