"""Train-step structure ablation probe (round-5 MFU isolation).

The r5 conv-fusion probe proved elementwise-after-conv is fused and the
tunnel sustains ~147 TFLOPs on pure bf16 conv chains, yet the full
ResNet-50 train step achieves only ~21.5.  This probe walks from the conv
chain TOWARD the train step one structural ingredient at a time, so the
expensive ingredient names itself:

  fwd                  conv(+relu) chain, forward only        (= r5 probe)
  fwd_bn               + training-mode BN (batch stats, fp32 params)
  grad                 value_and_grad of the chain, SGD update fused
  grad_bn              backward through conv+BN+relu, SGD update
  grad_bn_momentum     + momentum accumulators (the bench optimizer)
  grad_bn_mixed_dims   channel widths vary 64->256 like a real stage

All convs bf16 with fp32 params (AMP pattern: params fp32, cast to bf16
at use; grads come back fp32 via the cast's transpose).  FLOPs counted as
fwd=1x, grad=3x conv FLOPs (the standard train accounting the bench uses).

Usage: python tools/train_step_probe.py [N_LAYERS HW CH BATCH]
Emits one JSON line per variant.  PROBE_PLATFORM=cpu for smoke runs.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax

if os.environ.get("PROBE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])

import jax.numpy as jnp
import numpy as np
from jax import lax

N_LAYERS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
HW = int(sys.argv[2]) if len(sys.argv) > 2 else 56
CH = int(sys.argv[3]) if len(sys.argv) > 3 else 256
BATCH = int(sys.argv[4]) if len(sys.argv) > 4 else 64
STEPS = 8
DN = ("NCHW", "OIHW", "NCHW")


def conv(x, w):
    return lax.conv_general_dilated(x, w.astype(jnp.bfloat16), (1, 1),
                                    "SAME", dimension_numbers=DN)


def make_params(key, chans):
    params = []
    for cin, cout in zip(chans[:-1], chans[1:]):
        key, k1 = jax.random.split(key)
        params.append({
            "w": jax.random.normal(k1, (cout, cin, 3, 3), jnp.float32) * 0.05,
            "gamma": jnp.ones((cout,), jnp.float32),
            "beta": jnp.zeros((cout,), jnp.float32),
        })
    return params


def fwd_chain(params, x, use_bn):
    for p in params:
        y = conv(x, p["w"])
        if use_bn:
            # training-mode BN: batch statistics over N,H,W in fp32
            yf = jnp.float32(y)
            mean = yf.mean(axis=(0, 2, 3), keepdims=True)
            var = yf.var(axis=(0, 2, 3), keepdims=True)
            yn = (yf - mean) * lax.rsqrt(var + 1e-5)
            y = (yn * p["gamma"][None, :, None, None]
                 + p["beta"][None, :, None, None]).astype(jnp.bfloat16)
        x = jax.nn.relu(y)
    return jnp.float32(x).mean()


def chain_flops(chans, hw, batch):
    return sum(2 * batch * hw * hw * cin * cout * 9
               for cin, cout in zip(chans[:-1], chans[1:]))


def run(kind, fn, args, flops):
    from _probe_timing import run_timed

    run_timed(kind, fn, args, flops, STEPS,
              loss_of=lambda r: r[0] if isinstance(r, tuple) else r)


def main():
    key = jax.random.PRNGKey(0)
    chans = [CH] * (N_LAYERS + 1)
    params = make_params(key, chans)
    x = jax.random.normal(key, (BATCH, CH, HW, HW), jnp.bfloat16) * 0.1
    f1 = chain_flops(chans, HW, BATCH)

    fwd = jax.jit(functools.partial(fwd_chain, use_bn=False))
    run("fwd", fwd, (params, x), f1)
    fwd_bn = jax.jit(functools.partial(fwd_chain, use_bn=True))
    run("fwd_bn", fwd_bn, (params, x), f1)

    def train_step(params, x, use_bn, momentum):
        loss, grads = jax.value_and_grad(
            lambda p: fwd_chain(p, x, use_bn))(params)
        if momentum is not None:
            momentum = jax.tree.map(lambda m, g: 0.9 * m + g,
                                    momentum, grads)
            new = jax.tree.map(lambda p, m: p - 0.1 * m, params, momentum)
        else:
            new = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        return loss, new, momentum

    grad = jax.jit(functools.partial(train_step, use_bn=False,
                                     momentum=None))
    run("grad", grad, (params, x), 3 * f1)
    grad_bn = jax.jit(functools.partial(train_step, use_bn=True,
                                        momentum=None))
    run("grad_bn", grad_bn, (params, x), 3 * f1)
    mom = jax.tree.map(jnp.zeros_like, params)
    grad_bn_m = jax.jit(lambda p, x, m: train_step(p, x, True, m))
    run("grad_bn_momentum", grad_bn_m, (params, x, mom), 3 * f1)

    # realistic stage mix: widths change through the chain
    mixed = [64, 64, 128, 128, 256, 256, 256, 256, 256][: N_LAYERS + 1]
    params2 = make_params(key, mixed)
    x2 = jax.random.normal(key, (BATCH, mixed[0], HW, HW), jnp.bfloat16)
    f2 = chain_flops(mixed, HW, BATCH)
    grad_mixed = jax.jit(functools.partial(train_step, use_bn=True,
                                           momentum=None))
    run("grad_bn_mixed_dims", grad_mixed, (params2, x2), 3 * f2)


if __name__ == "__main__":
    main()
