#!/bin/bash
# Detached TPU liveness probe loop. Writes status to /root/repo/.tpu_status:
#   "wedged <timestamp> <n_attempts>" while the tunnel hangs,
#   "alive <timestamp>" once a tiny matmul completes — then exits.
# Probes are spaced far apart (7 min) and tiny, to avoid stacking work on a
# wedged tunnel (see docs/PERF.md wedge notes).
STATUS=/root/repo/.tpu_status
N=0
while true; do
  N=$((N+1))
  if timeout 120 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform == 'tpu', d
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
" >/dev/null 2>&1; then
    echo "alive $(date -u +%FT%TZ)" > "$STATUS"
    exit 0
  fi
  echo "wedged $(date -u +%FT%TZ) $N" > "$STATUS"
  sleep 420
done
