#!/usr/bin/env python
"""Runtime-contract repo linter (tier-1 CI; tests/test_repo_lint.py).

AST-walks ``paddle_tpu/`` and fails on two defect classes this codebase
has actually shipped, plus doc drift:

 1. **racy-dict** — a subscript/augmented write to a module-level (or
    class-level) mutable dict from function scope with no enclosing
    ``with <...lock...>:`` block.  This is the PR 5 profiler-race class:
    unlocked read-modify-write on shared module state drops updates under
    serving/guardian/trainer concurrency.  Import-time writes (module or
    class body, decorator-driven registries called during import) are
    exempt; reviewed exceptions live in ``ALLOWLIST`` with justification.

 2. **undeclared-env** — any ``PADDLE_*`` string literal (env knob name)
    not declared in ``paddle_tpu/fluid/envcontract.py``.  Every knob must
    be declared (name/type/default/subsystem) so docs/ENV.md and the
    verifier's env contract stay exhaustive.

 3. **env-doc-drift** — ``docs/ENV.md`` differs from the generator
    output (``python -m paddle_tpu.fluid.envcontract``).

Exit 0 = clean, 1 = findings (printed one per line as
``<class>:<file>:<line>: <message>``).
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENV_KEY_RE = re.compile(r"^PADDLE_[A-Z0-9_]*$")

#: (path relative to repo, dict name) -> justification.  Reviewed
#: exceptions ONLY; a new unlocked write needs a lock or an entry here.
ALLOWLIST: Dict[Tuple[str, str], str] = {
    ("paddle_tpu/fluid/layers/io.py", "_READERS"):
        "reader registration happens on the build thread before any "
        "consumer starts; readers are keyed by unique var name",
    ("paddle_tpu/ops/registry.py", "REGISTRY"):
        "op registration is import-time only (ops/__init__ imports every "
        "module once under the import lock)",
    ("paddle_tpu/ops/registry.py", "INFER_REGISTRY"):
        "same import-time registration as REGISTRY",
    ("paddle_tpu/fluid/ir.py", "_passes"):
        "pass registration is decorator-driven at import time",
    ("paddle_tpu/fluid/envcontract.py", "REGISTRY"):
        "knob declaration is module-body-driven at import time",
    ("paddle_tpu/fluid/amp.py", "_state"):
        "execution-mode toggles are set during single-threaded model "
        "build (enable/disable), read-only during traced execution",
    ("paddle_tpu/fluid/core.py", "GLOBAL_FLAGS"):
        "init_gflags runs at process startup before any worker thread",
}


class _FileLint(ast.NodeVisitor):
    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.findings: List[Tuple[str, int, str]] = []
        # module-level and class-level names bound to mutable dicts
        self.dicts: Set[str] = set()
        for node in tree.body:
            self._collect_dicts(node, self.dicts)
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    self._collect_dicts(sub, self.dicts)
        self._func_depth = 0
        self._with_lock_depth = 0

    @staticmethod
    def _collect_dicts(node, out: Set[str]) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        is_dict = isinstance(value, ast.Dict) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "OrderedDict", "defaultdict"))
        if not is_dict:
            return
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)

    # -- lock / function scope tracking --
    @staticmethod
    def _mentions_lock(expr: ast.expr) -> bool:
        for sub in ast.walk(expr):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name and "lock" in name.lower():
                return True
        return False

    def visit_With(self, node: ast.With):
        locked = any(self._mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self._with_lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_lock_depth -= 1

    def visit_FunctionDef(self, node):
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- check 1: racy dict writes --
    def _dict_name(self, target) -> str:
        """The shared-dict name a subscript write hits, or ''."""
        if not isinstance(target, ast.Subscript):
            return ""
        base = target.value
        if isinstance(base, ast.Name) and base.id in self.dicts:
            return base.id
        if isinstance(base, ast.Attribute) and base.attr in self.dicts:
            return base.attr
        return ""

    def _check_write(self, node, target) -> None:
        name = self._dict_name(target)
        if not name:
            return
        if self._func_depth == 0 or self._with_lock_depth > 0:
            return  # import-time or lock-protected
        if (self.relpath, name) in ALLOWLIST:
            return
        self.findings.append((
            "racy-dict", node.lineno,
            f"unlocked write to shared module dict '{name}' from function "
            f"scope — hold a lock (with <..lock..>:) or add a reviewed "
            f"ALLOWLIST entry in tools/repo_lint.py"))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_write(node, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_write(node, node.target)
        self.generic_visit(node)

    # -- check 2: undeclared PADDLE_* env keys --
    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str) and _ENV_KEY_RE.match(node.value):
            self.findings.append(("env-key", node.lineno, node.value))
        self.generic_visit(node)


def lint_file(path: str, declared) -> List[Tuple[str, str, int, str]]:
    relpath = os.path.relpath(path, REPO)
    with open(path, "r") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [("syntax", relpath, e.lineno or 0, str(e))]
    v = _FileLint(relpath, tree)
    v.visit(tree)
    out = []
    for kind, lineno, msg in v.findings:
        if kind == "env-key":
            if relpath.endswith("fluid/envcontract.py") or declared(msg):
                continue
            out.append((
                "undeclared-env", relpath, lineno,
                f"env knob {msg!r} is not declared in "
                f"paddle_tpu/fluid/envcontract.py — declare it (name, "
                f"type, default, subsystem) so docs/ENV.md stays "
                f"exhaustive"))
        else:
            out.append((kind, relpath, lineno, msg))
    return out


def check_env_doc() -> List[Tuple[str, str, int, str]]:
    from paddle_tpu.fluid import envcontract

    path = os.path.join(REPO, "docs", "ENV.md")
    want = envcontract.generate_markdown().strip()
    try:
        with open(path) as f:
            have = f.read().strip()
    except OSError:
        have = ""
    if have != want:
        return [("env-doc-drift", "docs/ENV.md", 0,
                 "stale — regenerate with `python -m "
                 "paddle_tpu.fluid.envcontract > docs/ENV.md`")]
    return []


def check_fault_doc() -> List[Tuple[str, str, int, str]]:
    """docs/FAULTS.md must match the chaos-schedule generator — a new
    PADDLE_FAULT_* hook cannot ship undocumented or invisible to the
    seeded drills (ISSUE 18)."""
    # the submodule directly: the chaos package __init__ pulls in the
    # drill runner, which the linter has no business importing
    from paddle_tpu.chaos import schedule as chaos_schedule

    path = os.path.join(REPO, "docs", "FAULTS.md")
    want = chaos_schedule.generate_fault_table().strip()
    try:
        with open(path) as f:
            have = f.read().strip()
    except OSError:
        have = ""
    if have != want:
        return [("fault-doc-drift", "docs/FAULTS.md", 0,
                 "stale — regenerate with `python -m paddle_tpu.chaos "
                 "faults --write`")]
    uncovered = chaos_schedule.uncovered_knobs()
    if uncovered:
        return [("fault-catalog-gap", "paddle_tpu/chaos/schedule.py", 0,
                 f"fault knob(s) {uncovered} are declared in envcontract "
                 f"but neither samplable in the chaos catalog nor "
                 f"explicitly exempt/excluded — add a CATALOG entry or "
                 f"an exclusion rationale")]
    return []


def run(root: str = None) -> List[Tuple[str, str, int, str]]:
    sys.path.insert(0, REPO)
    from paddle_tpu.fluid import envcontract

    root = root or os.path.join(REPO, "paddle_tpu")
    findings: List[Tuple[str, str, int, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, fn),
                                          envcontract.declared))
    if os.path.abspath(root) == os.path.join(REPO, "paddle_tpu"):
        findings.extend(check_env_doc())
        findings.extend(check_fault_doc())
    return findings


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=None,
                   help="tree to lint (default: <repo>/paddle_tpu)")
    args = p.parse_args(argv)
    findings = run(args.root)
    for kind, relpath, lineno, msg in findings:
        print(f"{kind}:{relpath}:{lineno}: {msg}")
    if findings:
        print(f"repo_lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("repo_lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
