"""Dump the public API signatures, one per line, for API-diff checks
(ref: tools/print_signatures.py / tools/diff_api.py — the reference's CI
compares this listing against a golden file to catch accidental API
breaks).

Usage: python tools/print_signatures.py [module] > API.spec
       python tools/print_signatures.py --diff API.spec [module]
"""

from __future__ import annotations

import hashlib
import importlib
import inspect
import os
import sys


def _signature_of(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def walk(module_name: str):
    """'qualified.name sig' lines for every public callable reachable from
    the module's __all__ (or public attrs), submodules up to 3 deep — the
    surface the reference's tool enumerates."""
    mod = importlib.import_module(module_name)
    seen, out = set(), []

    def emit(prefix, obj, depth=0):
        if depth > 3:
            return
        names = getattr(obj, "__all__", None) or \
            [n for n in dir(obj) if not n.startswith("_")]
        for n in sorted(names):
            try:
                a = getattr(obj, n)
            except AttributeError:
                continue
            q = f"{prefix}.{n}"
            if q in seen:
                continue
            seen.add(q)
            if inspect.ismodule(a):
                if getattr(a, "__name__", "").startswith(module_name):
                    emit(q, a, depth + 1)
            elif inspect.isclass(a):
                out.append(f"{q} {_signature_of(a)}")
                for m in sorted(vars(a)):
                    if m.startswith("_"):
                        continue
                    # getattr, not the raw descriptor: classmethods/
                    # staticmethods only look callable once bound
                    fn = getattr(a, m, None)
                    if not callable(fn):
                        continue
                    out.append(f"{q}.{m} {_signature_of(fn)}")
            elif callable(a):
                out.append(f"{q} {_signature_of(a)}")

    emit(module_name, mod)
    return out


def main(argv):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if argv and argv[0] == "--diff":
        golden = open(argv[1]).read().splitlines()
        golden = [l for l in golden if l and not l.startswith("#")]
        current = walk(argv[2] if len(argv) > 2 else "paddle_tpu.fluid")
        removed = sorted(set(golden) - set(current))
        added = sorted(set(current) - set(golden))
        for line in removed:
            print(f"- {line}")
        for line in added:
            print(f"+ {line}")
        return 1 if removed else 0
    module = argv[0] if argv else "paddle_tpu.fluid"
    lines = walk(module)
    for line in lines:
        print(line)
    digest = hashlib.md5("\n".join(lines).encode()).hexdigest()
    print(f"# {len(lines)} symbols, md5 {digest}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
