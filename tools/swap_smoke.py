#!/usr/bin/env python
"""Hot model swap smoke (CPU, < 10 s) — the ISSUE 16 CI oracle.

One decode engine, end to end through the registry lifecycle:

 1. serve baseline traffic on serial 0;
 2. commit serial 1 under the ``_SUCCESS`` protocol and hot-swap it
    while a stream is MID-GENERATION (immediate policy): the stream
    finishes its full budget — zero shed — and fresh traffic serves
    the new weights;
 3. commit serial 2 NaN-poisoned via ``PADDLE_FAULT_CKPT_POISON_SERIAL``
    (structurally valid, numerically garbage): the canary sentinel
    trips on its first probation tick and auto-rolls back to serial 1,
    vetoing serial 2 forever — with traffic still served throughout;
 4. the compile counter stays FLAT across both swaps AND the rollback
    (fixed-executable-set invariant), and post-rollback traffic is
    bitwise the pre-poison engine (K/V scrub).

Run directly (``python tools/swap_smoke.py``) or from tier-1 via
``tests/test_model_swap.py::test_swap_smoke_tool_runs_clean``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> dict:
    import numpy as np

    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (DecodeEngine, ModelRegistry,
                                    write_weights_serial)

    t_start = time.perf_counter()
    report = {"ok": False}
    eng = None
    try:
        model = transformer.DecodeModel(cfg=transformer.decode_lm_config(),
                                        max_slots=4, max_len=64,
                                        prefill_buckets=[4, 8])
        eng = DecodeEngine(model)
        report["executables_after_warmup"] = eng.warmup()
        m0 = eng.metrics.snapshot()

        rng = np.random.RandomState(11)
        prompts = [[int(t) for t in rng.randint(2, model.vocab_size - 1,
                                                size=3)]
                   for _ in range(3)]
        names = model.weight_names()
        w0 = eng.snapshot_weights(names)

        def perturbed(seed):
            prng = np.random.RandomState(seed)
            out = {}
            for n in sorted(w0):
                a = np.asarray(w0[n])
                out[n] = (a + 0.05 * prng.normal(size=a.shape)
                          ).astype(a.dtype) \
                    if np.issubdtype(a.dtype, np.floating) \
                    else np.array(a, copy=True)
            return out

        ckpt_root = tempfile.mkdtemp(prefix="swap_smoke_")
        reg = ModelRegistry(eng, ckpt_root, policy="immediate",
                            canary_requests=2, serial=0)

        # -- 1. baseline traffic on serial 0
        base = [eng.generate(p, 6) for p in prompts]

        # -- 2. commit serial 1, swap it in mid-generation, promote
        write_weights_serial(ckpt_root, 1, perturbed(seed=3))
        fut = eng.submit(prompts[0], 24)
        deadline = time.perf_counter() + 5
        while not eng._n_active and time.perf_counter() < deadline:
            time.sleep(0.002)
        report["swap_serial"] = reg.poll_once()
        report["midflight_tokens"] = len(fut.result(timeout=60))
        # probation traffic (2 completions incl. the mid-flight one)
        after_swap = eng.generate(prompts[1], 6)
        reg.poll_once()  # settles the promotion off-tick if needed
        report["serial_after_swap"] = reg.serial
        report["new_weights_serving"] = after_swap != base[1]

        # -- 3. commit serial 2 POISONED: canary must auto-rollback
        _fault.install(_fault.FaultPlan(ckpt_poison_serial=2))
        try:
            write_weights_serial(ckpt_root, 2, perturbed(seed=4))
        finally:
            _fault.clear()
        report["poison_swap_serial"] = reg.poll_once()
        served = eng.generate(prompts[2], 6)  # trips the sentinel
        report["served_during_canary"] = len(served)
        deadline = time.perf_counter() + 5
        while reg.serial != 1 and time.perf_counter() < deadline:
            time.sleep(0.002)
        report["serial_after_rollback"] = reg.serial
        report["vetoed"] = reg.vetoed()
        report["repoll_after_veto"] = reg.poll_once()

        # -- 4. invariants across the whole lifecycle
        with eng._dispatch_lock:  # back to serial 0 for the bitwise check
            eng._rebind_weights(w0)
            eng._scrub_caches()
        report["post_rollback_bitwise"] = \
            [eng.generate(p, 6) for p in prompts] == base
        snap = eng.metrics.snapshot()
        report["compiles_delta"] = \
            snap["bucket_compiles"] - m0["bucket_compiles"]
        report["shed_delta"] = snap["shed"] - m0["shed"]
        report["swaps"] = snap["model_swaps"]
        report["rollbacks"] = snap["model_rollbacks"]
        report["elapsed_s"] = round(time.perf_counter() - t_start, 2)
        report["ok"] = bool(
            report["swap_serial"] == 1
            and report["midflight_tokens"] == 24
            and report["serial_after_swap"] == 1
            and report["new_weights_serving"]
            and report["poison_swap_serial"] == 2
            and report["served_during_canary"] == 6
            and report["serial_after_rollback"] == 1
            and report["vetoed"] == [2]
            and report["repoll_after_veto"] is None
            and report["post_rollback_bitwise"]
            and report["compiles_delta"] == 0
            and report["shed_delta"] == 0
            and report["swaps"] == 2
            and report["rollbacks"] == 1)
    except Exception as exc:  # a broken smoke must still print its JSON
        import traceback

        report["error"] = f"{type(exc).__name__}: {exc}"
        report["trace"] = traceback.format_exc(limit=5)
    finally:
        if eng is not None:
            try:
                eng.shutdown(timeout_s=10)
            except Exception:
                pass
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
